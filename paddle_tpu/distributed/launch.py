"""Multi-process training launcher — the TPU-native analog of the
reference's cluster tooling (`paddle/scripts/submit_local.sh.in` `paddle`
CLI wrapper and `paddle/scripts/cluster_train/` fabric launchers): one
command that spawns a local cluster with the PADDLE_* env contract wired.

Two modes:

- collective (default, the "nccl2"/multi-host DP path):
    python -m paddle_tpu.distributed.launch --nproc 2 train.py [args...]
  Each rank gets PADDLE_TRAINER_ID / PADDLE_TRAINERS /
  PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT; scripts call
  `paddle_tpu.distributed.init_collective()` (rank-0 endpoint is the
  jax.distributed coordinator).  `--pservers N` makes the job HYBRID:
  pserver roles spawn first (PADDLE_PSERVER_EPS wired for everyone) and
  carry only sparse/embedding traffic, while dense grads ride the mesh
  (DistributeTranspiler mode="collective").

- pserver (the transpiler's parameter-server path):
    python -m paddle_tpu.distributed.launch --mode pserver \
        --nproc 2 --pservers 2 train.py [args...]
  Spawns pserver roles first (PADDLE_TRAINING_ROLE=PSERVER with
  PADDLE_CURRENT_ENDPOINT), waits for their ports, then trainer roles
  (PADDLE_TRAINING_ROLE=TRAINER with PADDLE_TRAINER_ID); all share
  PADDLE_PSERVER_EPS / PADDLE_TRAINERS.

Output is streamed line-by-line with a [role.rank] prefix.  The first
non-zero child exit kills the whole cluster (exception_holder.h's
fail-fast contract, process-level); the launcher returns that code.
"""

import argparse
import os
import shlex
import socket
import subprocess
import sys
import threading
import time


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(endpoint, timeout=60, cluster=None):
    """Poll until the endpoint accepts connections; abort early (False)
    if any already-spawned child has died — waiting out the full timeout
    on a crashed pserver would mask its exit code."""
    host, port = endpoint.rsplit(":", 1)
    t0 = time.time()
    while time.time() - t0 < timeout:
        try:
            socket.create_connection((host, int(port)), timeout=1).close()
            return True
        except OSError:
            # any exit — clean or not — before the port binds means this
            # cluster can never come up; abort instead of burning the
            # timeout (no pserver legitimately exits before listening)
            if cluster is not None and any(
                p.poll() is not None for _, p, _ in cluster.procs
            ):
                return False
            time.sleep(0.2)
    return False


class _RestartPolicy:
    """Supervisor restart budget: at most `max_restarts` within a sliding
    `window_s`, with exponential backoff between attempts.  next_delay()
    returns the backoff for one more restart, or None when the budget is
    exhausted (the death is then a real failure)."""

    def __init__(self, max_restarts=3, window_s=60.0, backoff_s=0.5):
        self.max_restarts = int(max_restarts)
        self.window_s = float(window_s)
        self.backoff_s = float(backoff_s)
        self._history = []

    def next_delay(self):
        now = time.monotonic()
        self._history = [t for t in self._history
                         if now - t < self.window_s]
        if len(self._history) >= self.max_restarts:
            return None
        delay = self.backoff_s * (2.0 ** len(self._history))
        self._history.append(now)
        return delay


class _ScalingPolicy:
    """Elastic autoscaling decision state (`--elastic MIN:MAX`,
    docs/FAULT_TOLERANCE.md "Elastic autoscaling"): the supervisor
    watches per-trainer STEP progress off the child output pump and
    decides, at most one action at a time,

      * GROW   — spare capacity (live < max) and every live trainer has
        made step progress for `hysteresis` consecutive observations
        (a struggling fleet is not helped by more mouths at the same
        pservers);
      * SHRINK — live > min and one trainer's step rate has sat below
        `straggler_frac` of the fleet median for `hysteresis`
        consecutive observations (retiring a straggler lets the sync
        round stop pacing itself on it).

    Flap damping rides the SAME _RestartPolicy machinery the supervisor
    uses for restart budgets: every action draws from an action budget
    (at most `max_actions` per `window_s`, exponential backoff between
    them) and a fixed `cooldown_s` separates consecutive actions — a
    noisy observation cannot thrash the membership."""

    def __init__(self, min_t, max_t, cooldown_s=3.0, hysteresis=2,
                 straggler_frac=0.5, budget=None, min_ps=None,
                 max_ps=None, queue_hi=None, min_pools=None,
                 max_pools=None, occ_hi=0.85, occ_lo=0.25):
        assert 1 <= int(min_t) <= int(max_t), (min_t, max_t)
        self.min_t = int(min_t)
        self.max_t = int(max_t)
        self.cooldown_s = float(cooldown_s)
        self.hysteresis = max(1, int(hysteresis))
        self.straggler_frac = float(straggler_frac)
        self.budget = budget or _RestartPolicy(
            max_restarts=6, window_s=120.0, backoff_s=0.0)
        self._last_action = time.monotonic()
        self._grow_streak = 0
        self._lag_streaks = {}
        # ---- load-aware PSERVER scaling (live shard migration) ------
        # the supervisor polls each live pserver's `stats` verb and
        # feeds the SERVER-side load here: queue_depth (un-applied
        # contributions backing up), staleness parks (async servers
        # pacing the fleet), and stale-plan drops (membership still
        # settling — an action-suppressing flap signal).  Same
        # hysteresis / cooldown / action-budget damping as the trainer
        # axis; pserver actions trigger shard MIGRATIONS, so the budget
        # matters twice over.
        self.min_ps = int(min_ps) if min_ps is not None else None
        self.max_ps = int(max_ps) if max_ps is not None else None
        # queue_hi: pending contributions at/above this read as "the
        # server cannot keep up" — default one full round's backlog
        self.queue_hi = queue_hi
        self._ps_hi_streak = 0
        self._ps_lo_streak = 0
        self._last_parks = None
        self._last_drops = None
        # ---- load-aware SERVING-POOL scaling (serving fabric) -------
        # third axis of the SAME policy instance: the supervisor polls
        # the FabricRouter's `stats` verb (the router speaks the same
        # shape the pservers do) and feeds fabric load here — queue
        # depth, mean occupancy, rejection and re-placement counters.
        # One shared cooldown + ONE action budget across trainers,
        # pservers, and pools: the three axes cannot fight each other,
        # because every membership change anywhere draws from the same
        # allowance.
        self.min_pools = int(min_pools) if min_pools is not None else None
        self.max_pools = int(max_pools) if max_pools is not None else None
        self.occ_hi = float(occ_hi)
        self.occ_lo = float(occ_lo)
        self._pool_hi_streak = 0
        self._pool_lo_streak = 0
        self._last_rejected = None
        self._last_replaced = None

    def observe_ps_load(self, ps_count, load, n_trainers=2):
        """One pserver-load observation -> optional pserver action.
        `load` aggregates the live servers' stats: {"queue_depth": max
        across servers, "staleness_parks": cumulative, and
        "stale_plan_drops": cumulative}.  Returns ("grow_ps", None),
        ("shrink_ps", None) or None.  Shares the cooldown + action
        budget with the trainer axis — one membership change at a
        time."""
        if self.min_ps is None or self.max_ps is None or not load:
            return None
        now = time.monotonic()
        qd = int(load.get("queue_depth", 0))
        parks = int(load.get("staleness_parks", 0))
        drops = int(load.get("stale_plan_drops", 0))
        parks_d = parks - (self._last_parks
                           if self._last_parks is not None else parks)
        drops_d = drops - (self._last_drops
                           if self._last_drops is not None else drops)
        self._last_parks, self._last_drops = parks, drops
        hi = (self.queue_hi if self.queue_hi is not None
              else max(2, int(n_trainers)))
        if drops_d > 0:
            # stale-plan drops mean a membership change is still
            # settling: acting on load measured mid-flap would thrash
            self._ps_hi_streak = 0
            self._ps_lo_streak = 0
            return None
        if qd >= hi or parks_d > 0:
            self._ps_hi_streak += 1
            self._ps_lo_streak = 0
        elif qd == 0:
            self._ps_lo_streak += 1
            self._ps_hi_streak = 0
        else:
            self._ps_hi_streak = 0
            self._ps_lo_streak = 0
        if now - self._last_action < self.cooldown_s:
            return None
        action = None
        if (self._ps_hi_streak >= self.hysteresis
                and ps_count < self.max_ps):
            action = ("grow_ps", None)
        elif (self._ps_lo_streak >= 2 * self.hysteresis
                and ps_count > self.min_ps):
            # retiring a server migrates every one of its shards: ask
            # for twice the evidence a grow needs
            action = ("shrink_ps", None)
        if action is None:
            return None
        if self.budget.next_delay() is None:
            sys.stderr.write(
                "[launch] elastic pserver action %r suppressed: action "
                "budget exhausted (flap damping)\n" % (action[0],))
            return None
        self._last_action = now
        self._ps_hi_streak = 0
        self._ps_lo_streak = 0
        return action

    def observe_pool_load(self, n_pools, load):
        """One fabric-load observation -> optional serving-pool action.
        `load` is the FabricRouter's stats(): {"queue_depth": fabric
        admission queue, "occupancy": mean live-pool slot occupancy,
        "rejected"/"replaced": cumulative counters (diffed here)}.
        Returns ("grow_pool", None), ("shrink_pool", None) or None.
        Shares the cooldown and the action budget with the trainer and
        pserver axes — ONE membership change at a time, fabric-wide."""
        if self.min_pools is None or self.max_pools is None or not load:
            return None
        now = time.monotonic()
        qd = int(load.get("queue_depth", 0))
        occ = float(load.get("occupancy", 0.0))
        rej = int(load.get("rejected", 0))
        repl = int(load.get("replaced", 0))
        rej_d = rej - (self._last_rejected
                       if self._last_rejected is not None else rej)
        repl_d = repl - (self._last_replaced
                         if self._last_replaced is not None else repl)
        self._last_rejected, self._last_replaced = rej, repl
        if repl_d > 0:
            # re-placements mean a pool just died and its requests are
            # re-decoding on survivors: occupancy/queue measured mid-
            # failover would read as organic pressure and thrash
            self._pool_hi_streak = 0
            self._pool_lo_streak = 0
            return None
        if qd > 0 or occ >= self.occ_hi or rej_d > 0:
            self._pool_hi_streak += 1
            self._pool_lo_streak = 0
        elif occ <= self.occ_lo:
            self._pool_lo_streak += 1
            self._pool_hi_streak = 0
        else:
            self._pool_hi_streak = 0
            self._pool_lo_streak = 0
        if now - self._last_action < self.cooldown_s:
            return None
        action = None
        if (self._pool_hi_streak >= self.hysteresis
                and n_pools < self.max_pools):
            action = ("grow_pool", None)
        elif (self._pool_lo_streak >= 2 * self.hysteresis
                and n_pools > self.min_pools):
            # retiring a pool drains every in-flight request off it:
            # ask for twice the evidence a grow needs
            action = ("shrink_pool", None)
        if action is None:
            return None
        if self.budget.next_delay() is None:
            sys.stderr.write(
                "[launch] elastic pool action %r suppressed: action "
                "budget exhausted (flap damping)\n" % (action[0],))
            return None
        self._last_action = now
        self._pool_hi_streak = 0
        self._pool_lo_streak = 0
        return action

    def decide(self, live_tags, rates):
        """One observation -> one decision.  `rates` maps live tag ->
        steps/s over the recent window (None = no step seen yet).
        Returns ("grow", None), ("shrink", tag) or None."""
        now = time.monotonic()
        n = len(live_tags)
        known = {t: r for t, r in rates.items()
                 if t in live_tags and r is not None}
        # hysteresis bookkeeping runs every observation (even inside the
        # cooldown) so a persistent condition acts the moment damping
        # allows, while a transient one decays away
        if n < self.max_t and len(known) == n and n > 0 \
                and all(r > 0 for r in known.values()):
            self._grow_streak += 1
        else:
            self._grow_streak = 0
        lagger = None
        if n > self.min_t and len(known) >= 2:
            # true median: for an even fleet the upper-middle element
            # would key the straggler threshold off a faster-than-
            # median rate and over-fire on exactly the 2-trainer fleets
            # --elastic produces
            vals = sorted(known.values())
            mid = len(vals) // 2
            med = (vals[mid] if len(vals) % 2
                   else 0.5 * (vals[mid - 1] + vals[mid]))
            for t, r in known.items():
                if med > 0 and r < self.straggler_frac * med:
                    self._lag_streaks[t] = self._lag_streaks.get(t, 0) + 1
                    if self._lag_streaks[t] >= self.hysteresis:
                        lagger = t
                else:
                    self._lag_streaks.pop(t, None)
        else:
            self._lag_streaks.clear()
        if now - self._last_action < self.cooldown_s:
            return None
        action = None
        if lagger is not None:
            action = ("shrink", lagger)
        elif self._grow_streak >= self.hysteresis and n < self.max_t:
            action = ("grow", None)
        if action is None:
            return None
        if self.budget.next_delay() is None:
            sys.stderr.write(
                "[launch] elastic action %r suppressed: action budget "
                "exhausted (flap damping)\n" % (action[0],))
            return None
        self._last_action = now
        self._grow_streak = 0
        if action[0] == "shrink":
            self._lag_streaks.pop(action[1], None)
        return action


class _Cluster:
    """Spawned children with streamed output and fail-fast teardown.

    Chaos hooks: `kill_one(tag)` / `schedule_kill(tag, after_s)` SIGKILL a
    single child, and tags passed to `expect_failure()` don't trip the
    fail-fast teardown — the point of a chaos run is that the SURVIVORS
    finish after a deliberate kill.

    Supervision (`supervise(tag, cmd, env, policy)`): a registered child
    that dies nonzero is RELAUNCHED with the same command and env (after
    the policy's backoff), instead of failing the cluster — the
    self-healing loop: a restarted pserver restores its checkpoint and
    peers re-fence; a restarted trainer re-registers and rejoins.  The
    death notification (`on_child_death`) always fires BEFORE the
    respawn, so a trainer ghost is evicted before its replacement
    registers."""

    def __init__(self):
        self.procs = []  # (tag, Popen, pump-thread)
        self._lock = threading.Lock()
        self.failed_rc = None
        self._expected_failures = set()  # tags whose death is deliberate
        self._excused = set()  # individual Popens excused by a respawn
        self._supervised = {}  # tag -> {"cmd": [...], "env": {...},
        #                                "policy": _RestartPolicy}
        self.restarts = {}  # tag -> respawn count (observability)
        self._respawns_pending = 0  # respawn backoffs in flight
        self._closing = threading.Event()
        # service children (serving-pool workers): they serve RPC until
        # told to stop, so they are excluded from the job-conclusion
        # scan, retired when the job concludes, and their deaths never
        # fail the cluster
        self.aux_tags = set()
        # called as (tag, rc) when a child exits nonzero — pserver mode
        # uses it to report trainer deaths to the control plane, closing
        # the window where a trainer dies BEFORE its first heartbeat
        # (never tracked, so never evicted) and would hang the sync round
        self.on_child_death = None
        # called as (tag) when the supervisor has DECIDED to respawn,
        # before the backoff/spawn — pserver mode pre-registers a dying
        # trainer's id so the job is not declared done while its
        # replacement is still booting.  Returning False cancels the
        # respawn (the job already completed without the child).
        self.on_respawn = None
        # called as (tag, rc) when a supervised child's restart budget
        # is EXHAUSTED (the death becomes a real failure) — pserver mode
        # sends the surviving pservers a TERMINAL evict so they stop
        # holding the job open for a replacement that will never come
        self.on_respawn_denied = None
        # called as (tag, line) for every pumped child output line —
        # the elastic scaling policy reads trainer STEP progress off it
        self.on_child_line = None

    def spawn(self, tag, cmd, env, aux=False):
        proc = subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            bufsize=1,
        )
        t = threading.Thread(target=self._pump, args=(tag, proc), daemon=True)
        with self._lock:
            if aux:
                self.aux_tags.add(tag)
            self.procs.append((tag, proc, t))
            closing = self._closing.is_set()
        if closing:
            # teardown raced this spawn (a supervised respawn slipping
            # past kill()'s proc snapshot): the child must not outlive
            # the launcher — it is registered above, so kill()/wait()
            # bookkeeping still sees it
            proc.kill()
        t.start()
        return proc

    def supervise(self, tag, cmd, env, policy=None):
        """Register `tag` for supervised restarts (see class docstring).
        Call after (or before) spawn(); the cmd/env given here are what a
        respawn uses."""
        self._supervised[tag] = {
            "cmd": list(cmd), "env": dict(env),
            "policy": policy or _RestartPolicy()}

    def unsupervise(self, tag):
        """Drop `tag` from supervision (elastic retirement: its coming
        death is deliberate and must NOT be respawned — the death
        notification then reports it as terminal)."""
        self._supervised.pop(tag, None)

    def _pump(self, tag, proc):
        try:
            for line in proc.stdout:
                sys.stdout.write("[%s] %s" % (tag, line))
                sys.stdout.flush()
                cb = self.on_child_line
                if cb is not None:
                    try:
                        cb(tag, line.rstrip("\n"))
                    except Exception:
                        pass  # an observer must never kill the pump
            rc = proc.wait()
        finally:
            try:
                proc.stdout.close()  # reap the pipe fd with the child
            except OSError:
                pass
        if rc == 0:
            return
        supervised = (tag in self._supervised
                      and not self._closing.is_set())
        if not supervised:
            # record the failure FIRST so fail-fast teardown isn't
            # delayed behind the (best-effort, up-to-seconds) death
            # notification RPCs
            self._record_failure(tag, rc)
            self._notify_death(tag, rc)
            return
        # supervised: death notification BEFORE the respawn — eviction
        # must land before the replacement registers, so the pserver
        # never sees the fresh incarnation and then an out-of-order
        # ghost report
        self._notify_death(tag, rc)
        if not self._respawn(tag, proc, rc):
            self._record_failure(tag, rc)

    def _record_failure(self, tag, rc):
        with self._lock:
            if tag in self._expected_failures:
                sys.stderr.write(
                    "[launch] %s exited rc=%d (expected chaos kill)\n"
                    % (tag, rc)
                )
            elif tag in self.aux_tags:
                # a service child dying (pool_proc_kill chaos, OOM)
                # degrades serving; it never fails the training job
                sys.stderr.write(
                    "[launch] %s exited rc=%d (service child — job "
                    "continues)\n" % (tag, rc)
                )
            elif self.failed_rc is None:
                self.failed_rc = rc
                sys.stderr.write(
                    "[launch] %s exited rc=%d — stopping cluster\n" % (tag, rc)
                )

    def _notify_death(self, tag, rc):
        cb = self.on_child_death
        if cb is not None:
            try:
                cb(tag, rc)
            except Exception as e:
                sys.stderr.write(
                    "[launch] death notification for %s failed: %s\n"
                    % (tag, e))

    def _respawn(self, tag, dead_proc, rc):
        """Supervised-restart path: returns True when the death was
        absorbed by a respawn (the dead Popen is excused from the exit
        scan)."""
        spec = self._supervised.get(tag)
        if spec is None or self._closing.is_set():
            return False
        delay = spec["policy"].next_delay()
        if delay is None:
            sys.stderr.write(
                "[launch] %s exited rc=%d — restart budget exhausted "
                "(max %d per %.0fs)\n"
                % (tag, rc, spec["policy"].max_restarts,
                   spec["policy"].window_s))
            # the earlier death notification promised a respawn
            # (respawn=True parked the id on every pserver); retract it
            # with a terminal report so survivors fail NOW instead of
            # serving a ghost until the eviction deadline
            hook = self.on_respawn_denied
            if hook is not None:
                try:
                    hook(tag, rc)
                except Exception as e:
                    sys.stderr.write(
                        "[launch] budget-exhaustion notification for %s "
                        "failed: %s\n" % (tag, e))
            return False
        hook = self.on_respawn
        if hook is not None:
            try:
                if hook(tag) is False:
                    sys.stderr.write(
                        "[launch] %s not respawned — the job completed "
                        "without it\n" % tag)
                    with self._lock:
                        self._excused.add(dead_proc)
                    return True
            except Exception as e:
                sys.stderr.write(
                    "[launch] respawn announcement for %s failed: %s\n"
                    % (tag, e))
        with self._lock:
            self._excused.add(dead_proc)
            self._respawns_pending += 1
            n = self.restarts[tag] = self.restarts.get(tag, 0) + 1
        try:
            sys.stderr.write(
                "[launch] supervisor restarting %s (rc=%d, restart #%d, "
                "backoff %.2fs)\n" % (tag, rc, n, delay))
            if self._closing.wait(delay):
                return True  # teardown raced the backoff: stay down
            try:
                self.spawn(tag, spec["cmd"], spec["env"])
            except Exception as e:
                # the replacement never started: this is a REAL failure,
                # not an absorbed death — without recording it, wait()
                # would skip the excused Popen and report success with
                # the child permanently missing
                sys.stderr.write(
                    "[launch] respawn of %s failed: %s\n" % (tag, e))
                with self._lock:
                    if self.failed_rc is None:
                        self.failed_rc = rc if rc != 0 else 1
        finally:
            with self._lock:
                self._respawns_pending -= 1
        return True

    def wait(self, poll=0.2):
        """Wait for all children; kill everything on first (unexpected)
        failure."""
        while True:
            with self._lock:
                failed = self.failed_rc
                procs = list(self.procs)
                respawning = self._respawns_pending
                aux = set(self.aux_tags)
            if failed is not None:
                self.kill()
                return failed
            # the JOB concludes on its primary children only — service
            # children (pool workers) serve RPC until told to stop, so
            # waiting on them would hang the launcher forever
            primary = [e for e in procs if e[0] not in aux]
            # conclusion needs every pump thread DEAD, not just every
            # child exited: a pump mid death-processing (notification
            # RPCs, respawn decision) hasn't excused its Popen yet, and
            # concluding in that window would misread a supervised death
            # as a cluster failure
            if (not respawning
                    and all(p.poll() is not None for _, p, _ in primary)
                    and all(not t.is_alive() for _, _, t in primary)):
                for _, _, t in primary:
                    t.join(timeout=5)
                self._shutdown_aux()
                # first nonzero (incl. negative signal-kill codes) wins —
                # max() would mask a SIGKILLed child behind a clean peer —
                # but a deliberately killed or respawned child doesn't
                # count
                for tag, p, _ in primary:
                    if (p.returncode != 0
                            and tag not in self._expected_failures
                            and p not in self._excused):
                        return p.returncode
                return 0
            time.sleep(poll)

    def _shutdown_aux(self):
        """The job has concluded: retire the service children that live
        exactly as long as it does.  SIGTERM, bounded wait, SIGKILL
        fallback — their exit codes never count against the job."""
        self._closing.set()  # retired service children are not respawned
        with self._lock:
            aux = [e for e in self.procs if e[0] in self.aux_tags]
        for tag, p, _ in aux:
            if p.poll() is None:
                sys.stderr.write("[launch] POOL WORKER RETIRE %s\n" % tag)
                p.terminate()
        for tag, p, t in aux:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            t.join(timeout=5)

    def kill(self):
        self._closing.set()  # cancel pending supervised respawns
        with self._lock:
            procs = list(self.procs)
        for _, p, _ in procs:
            if p.poll() is None:
                p.kill()
        for _, p, t in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            t.join(timeout=5)
            # the pump normally closes the pipe at EOF; make teardown
            # idempotent so repeated chaos tests never leak fds
            if p.stdout is not None:
                try:
                    p.stdout.close()
                except OSError:
                    pass

    # ---- chaos helpers (fault-injection harness) ----------------------
    def proc(self, tag):
        """The Popen for one child by its [role.rank] tag (the LATEST
        incarnation when the supervisor has respawned it)."""
        with self._lock:
            for t, p, _ in reversed(self.procs):
                if t == tag:
                    return p
        raise KeyError("no child tagged %r (have %s)"
                       % (tag, [t for t, _, _ in self.procs]))

    def expect_failure(self, tag):
        """Mark a child's death as deliberate: its nonzero exit neither
        tears the cluster down nor fails wait()."""
        with self._lock:
            self._expected_failures.add(tag)

    def kill_one(self, tag, sig=None):
        """SIGKILL (or `sig`) one child — simulated process death.  The
        tag is auto-marked as an expected failure."""
        import signal as _signal

        self.expect_failure(tag)
        p = self.proc(tag)
        if p.poll() is None:
            if sig is None or sig == _signal.SIGKILL:
                p.kill()
            else:
                p.send_signal(sig)
        return p

    def schedule_kill(self, tag, after_s, sig=None):
        """Arm a timer that kill_one()s `tag` after `after_s` seconds —
        the deterministic "trainer dies mid-round" chaos trigger."""
        self.proc(tag)  # a typo'd tag must fail NOW, not silently never
        # fire from the timer thread (rc=0 would read as "survivors rode
        # out the kill" when no fault was injected at all)
        self.expect_failure(tag)  # arm BEFORE the timer can race _pump
        t = threading.Timer(after_s, self.kill_one, args=(tag, sig))
        t.daemon = True
        t.start()
        return t


def _arm_chaos(cluster, chaos_kills):
    """chaos_kills: [(tag, after_s), ...] — arm deliberate child kills."""
    for tag, after_s in chaos_kills or []:
        cluster.schedule_kill(tag, after_s)


def drive_pserver_migration(old_world, new_world, attempts=3,
                            timeout_s=600.0, retry_wait=1.0,
                            delta=True):
    """Two-phase supervisor driver for a pserver-set change
    (docs/FAULT_TOLERANCE.md "Live shard migration").

    Phase 1 — `migrate_begin(new_world)` on EVERY involved server (old
    and new): each freezes at a round boundary, serializes the shards it
    owns under the old dispatch but not the new one as crc-framed
    journal records, and ships them to their new owners, which apply +
    fsync BEFORE acking.  Phase 2 — only after every begin acked,
    `migrate_commit(new_world)` on every server: adopt the world, drop
    moved state, mint the plan epoch.  The epoch therefore provably
    never mints before target durability; any failure aborts the whole
    attempt (old assignment stays authoritative, zero applied updates
    lost) and the driver retries — a SIGKILLed source or target
    restores and the next attempt re-captures fresh state.

    `delta=True` (the default): each source ships its bulky sparse
    tables as an UNFROZEN snapshot first and freezes only for the
    dirty-row final tail — `freeze_ms` in the result (max over the
    involved servers) is that frozen window, the serving-visible cost
    of the handoff, typically a small fraction of `ms`.

    Returns {"ok", "attempts", "moved", "bytes", "ms", "freeze_ms",
    "epochs"}."""
    import time as _t

    from .rpc import RPCClient

    old_world = [str(e) for e in old_world]
    new_world = [str(e) for e in new_world]
    involved = sorted(set(old_world) | set(new_world))
    last_err = None
    for attempt in range(1, int(attempts) + 1):
        t0 = _t.monotonic()
        begun, moved, nbytes = [], 0, 0
        freeze_ms = 0.0
        err = None
        for ep in involved:
            try:
                r = RPCClient.get(ep).call(
                    "migrate_begin", timeout_s=timeout_s,
                    world=new_world, delta=bool(delta))
            except Exception as e:
                err = "begin at %s failed: %s" % (ep, e)
                break
            if not (isinstance(r, dict) and r.get("ok")):
                err = "begin at %s refused: %r" % (ep, r)
                break
            begun.append(ep)
            moved += int(r.get("moved", 0))
            nbytes += int(r.get("bytes", 0))
            freeze_ms = max(freeze_ms, float(r.get("freeze_ms", 0.0)))
        if err is not None:
            last_err = err
            sys.stderr.write(
                "[launch] pserver migration attempt %d aborted: %s\n"
                % (attempt, err))
            for ep in begun:
                try:
                    RPCClient.get(ep).call("migrate_abort")
                except Exception:
                    pass
            _t.sleep(retry_wait * attempt)
            continue
        # every moving shard is durable at its target: commit (a server
        # killed between its begin-ack and here restores pre-handoff
        # state; its commit then reads stale and the WHOLE handoff
        # retries — the epoch still never minted early)
        epochs = {}
        for ep in involved:
            committed = False
            for _ in range(3):
                try:
                    r = RPCClient.get(ep).call(
                        "migrate_commit", timeout_s=timeout_s,
                        world=new_world)
                except Exception as e:
                    err = "commit at %s failed: %s" % (ep, e)
                    _t.sleep(retry_wait)
                    continue
                if isinstance(r, dict) and r.get("ok"):
                    epochs[ep] = int(r.get("epoch", 0))
                    committed = True
                    break
                err = "commit at %s stale: %r" % (ep, r)
                break
            if not committed:
                break
        if len(epochs) == len(involved):
            return {"ok": True, "attempts": attempt, "moved": moved,
                    "bytes": nbytes, "epochs": epochs,
                    "ms": round((_t.monotonic() - t0) * 1e3, 3),
                    "freeze_ms": round(freeze_ms, 3)}
        last_err = err
        sys.stderr.write(
            "[launch] pserver migration attempt %d commit failed: %s "
            "— restarting the handoff\n" % (attempt, err))
        for ep in involved:
            try:
                RPCClient.get(ep).call("migrate_abort")
            except Exception:
                pass
        _t.sleep(retry_wait * attempt)
    return {"ok": False, "error": last_err}


def launch_collective(script_argv, nproc, base_env=None, chaos_kills=None,
                      n_pservers=0):
    """Collective (mesh data-parallel) cluster: nproc trainer processes,
    one device each, mesh spanning them via jax.distributed.  With
    `n_pservers` > 0 the job is HYBRID: pserver roles spawn first and
    carry ONLY sparse/embedding traffic (PADDLE_PSERVER_EPS is wired for
    both roles); dense grads ride the mesh and never touch them."""
    eps = ",".join("127.0.0.1:%d" % free_port() for _ in range(nproc))
    cluster = _Cluster()
    ep_list = eps.split(",")
    common = dict(base_env or os.environ)
    common.update(
        PADDLE_TRAINERS=str(nproc),
        PADDLE_TRAINER_ENDPOINTS=eps,
    )
    ps_ports = [free_port() for _ in range(n_pservers)]
    if ps_ports:
        common["PADDLE_PSERVER_EPS"] = ",".join(
            "127.0.0.1:%d" % p for p in ps_ports)
    for i, p in enumerate(ps_ports):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="PSERVER",
            PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
        )
        cluster.spawn(
            "pserver.%d" % i, [sys.executable, "-u"] + script_argv, env)
    for p in ps_ports:
        if not _wait_port("127.0.0.1:%d" % p, cluster=cluster):
            sys.stderr.write("[launch] pserver port %d never opened\n" % p)
            dead = [pr.poll() for _, pr, _ in cluster.procs
                    if pr.poll() is not None]
            cluster.kill()
            bad = [rc for rc in dead if rc != 0]
            return bad[0] if bad else 1
    for rank in range(nproc):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="TRAINER",
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_CURRENT_ENDPOINT=ep_list[rank],
        )
        cluster.spawn(
            "trainer.%d" % rank, [sys.executable, "-u"] + script_argv, env
        )
    _arm_chaos(cluster, chaos_kills)
    return cluster.wait()


def _start_pserver_elastic_loop(cluster, common, script_argv, base_tags,
                                spare, min_ps, max_ps, schedule, cooldown,
                                supervise, make_restart_policy, stop_evt,
                                nproc, policy=None):
    """Elastic PSERVER loop (`--elastic-pservers MIN:MAX` /
    `--pserver-schedule`, docs/FAULT_TOLERANCE.md "Live shard
    migration"): grows a fresh (empty, PADDLE_PSERVER_ELASTIC=1) pserver
    child and drives the two-phase journaled shard handoff into it, or
    retires one by migrating every shard away, waiting for the trainers
    to complete a round under the new plan, and issuing a clean `retire`.
    Policy-driven actions read the live servers' `stats` verb — queue
    depth / staleness parks / stale-plan drops — through
    _ScalingPolicy.observe_ps_load; `--pserver-schedule T:+N,T:-N` is
    the deterministic bench/chaos driver on the same machinery."""
    from .rpc import RPCClient

    world = [ep for _tag, ep in base_tags]  # live pserver endpoints
    tag_of = {ep: tag for tag, ep in base_tags}
    grown = []  # (tag, ep), newest last — preferred retirement victims
    if policy is None:
        policy = _ScalingPolicy(1, max(1, nproc), cooldown_s=cooldown,
                                min_ps=min_ps, max_ps=max_ps)
    sched = []
    for spec in (schedule or "").split(","):
        spec = spec.strip()
        if spec:
            t_s, _, d = spec.partition(":")
            sched.append([float(t_s), int(d)])
    sched.sort(key=lambda e: e[0])
    scheduled_only = bool(sched)
    t_start = time.monotonic()

    def poll_stats(ep, timeout=1.5):
        cli = RPCClient(ep, timeout=1.0, retries=1, retry_wait=0.05)
        try:
            s = cli.call("stats", deadline_s=timeout)
            return s if isinstance(s, dict) else None
        except Exception:
            return None
        finally:
            cli.close()

    def poll_load():
        agg = {"queue_depth": 0, "staleness_parks": 0,
               "stale_plan_drops": 0}
        seen = False
        for ep in list(world):
            s = poll_stats(ep)
            if s is None:
                continue
            seen = True
            agg["queue_depth"] = max(agg["queue_depth"],
                                     int(s.get("queue_depth", 0)))
            agg["staleness_parks"] += int(s.get("staleness_parks", 0))
            agg["stale_plan_drops"] += int(s.get("stale_plan_drops", 0))
        return agg if seen else None

    def wait_round_advance(min_rounds=2, timeout=45.0):
        """Wait until the trainers have re-planned AWAY from the
        retiree before it disappears.  Sync mode: a surviving server's
        round counter advancing `min_rounds` past the commit means
        every live trainer completed a full round under the NEW plan
        (rounds are all-trainer barriers).  Async mode (no rounds): the
        survivor fencing the trainers' old-epoch frames
        (stale_plan_drops moving) is the re-plan witness — wait one
        cooldown past it for the recovery re-ship to land."""
        probe = next((e for e in world), None)
        if probe is None:
            return
        s = poll_stats(probe)
        base = int(s.get("round", 0)) if s else 0
        base_drops = int(s.get("stale_plan_drops", 0)) if s else 0
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout and not stop_evt.is_set():
            s = poll_stats(probe)
            if s and int(s.get("round", 0)) >= base + min_rounds:
                return
            if s and int(s.get("stale_plan_drops", 0)) > base_drops:
                stop_evt.wait(max(1.0, float(cooldown)))
                return
            if stop_evt.wait(0.3):
                return

    def grow_ps(reason):
        if not spare or len(world) >= max_ps:
            return
        tag, ep = spare.pop(0)
        env = dict(common, PADDLE_TRAINING_ROLE="PSERVER",
                   PADDLE_CURRENT_ENDPOINT=ep,
                   PADDLE_PSERVER_ELASTIC="1")
        cmd = [sys.executable, "-u"] + script_argv
        sys.stderr.write("[launch] ELASTIC PSERVER GROW %s at %s (%s)\n"
                         % (tag, ep, reason))
        if supervise:
            cluster.supervise(tag, cmd, env, make_restart_policy())
        cluster.spawn(tag, cmd, env)

        def reap_failed_grow(why):
            # a failed grow must not leak: unsupervise (a supervised
            # orphan would respawn forever outside every world), stop
            # the child, and RETURN the slot so grow capacity is not
            # permanently burned by a transient failure
            sys.stderr.write(
                "[launch] elastic pserver %s at %s abandoned: %s\n"
                % (tag, ep, why))
            cluster.unsupervise(tag)
            try:
                RPCClient.get(ep).call("retire", deadline_s=10.0)
            except Exception:
                cluster.kill_one(tag)
            spare.append((tag, ep))

        if not _wait_port(ep, timeout=120, cluster=cluster):
            reap_failed_grow("port never opened")
            return
        r = drive_pserver_migration(world, world + [ep])
        if r.get("ok"):
            world.append(ep)
            tag_of[ep] = tag
            grown.append((tag, ep))
            sys.stderr.write(
                "[launch] PSERVER MIGRATION ok: world=%d moved=%d "
                "bytes=%d ms=%.1f freeze_ms=%.1f\n"
                % (len(world), r["moved"], r["bytes"], r["ms"],
                   r.get("freeze_ms", 0.0)))
        else:
            reap_failed_grow(
                "migration failed (%s)" % r.get("error"))

    def shrink_ps(reason):
        if len(world) <= min_ps:
            return
        tag, ep = grown.pop() if grown else (tag_of[world[-1]],
                                             world[-1])
        sys.stderr.write(
            "[launch] ELASTIC PSERVER SHRINK %s at %s (%s)\n"
            % (tag, ep, reason))
        new_world = [e for e in world if e != ep]
        r = drive_pserver_migration(world, new_world)
        if not r.get("ok"):
            sys.stderr.write(
                "[launch] PSERVER MIGRATION failed (%s): %s stays\n"
                % (r.get("error"), tag))
            if (tag, ep) not in grown and ep in tag_of:
                grown.append((tag, ep))
            return
        world[:] = new_world
        sys.stderr.write(
            "[launch] PSERVER MIGRATION ok: world=%d moved=%d bytes=%d "
            "ms=%.1f freeze_ms=%.1f\n"
            % (len(world), r["moved"], r["bytes"], r["ms"],
               r.get("freeze_ms", 0.0)))
        # drain: every trainer must complete one round under the new
        # plan (its old-epoch frames got fenced, it re-planned away
        # from the retiree) before the retiree may disappear
        wait_round_advance()
        cluster.unsupervise(tag)
        try:
            RPCClient.get(ep).call("retire", deadline_s=10.0)
        except Exception:
            cluster.kill_one(tag)

    def loop():
        while not stop_evt.wait(0.5):
            if cluster._closing.is_set() or cluster.failed_rc is not None:
                return
            now = time.monotonic()
            if sched and now - t_start >= sched[0][0]:
                delta = sched.pop(0)[1]
                for _ in range(abs(delta)):
                    if delta > 0:
                        grow_ps("scheduled")
                    else:
                        shrink_ps("scheduled")
                continue
            if scheduled_only:
                continue
            load = poll_load()
            act = policy.observe_ps_load(len(world), load,
                                         n_trainers=nproc)
            if act is None:
                continue
            if act[0] == "grow_ps":
                grow_ps("policy: %s" % load)
            else:
                shrink_ps("policy: %s" % load)

    def run():
        try:
            loop()
        except Exception:
            import traceback

            sys.stderr.write("[launch] elastic pserver loop died:\n")
            traceback.print_exc()

    threading.Thread(target=run, daemon=True,
                     name="elastic-pserver-policy").start()


_CONTROL_POLICY = None


def _control_call(control_ep, verb, **kw):
    """ONE retry/deadline policy for every router/worker control RPC
    the launcher makes (rpc.CallPolicy — the same helper serving's
    ProcessPool backend rides): bounded attempts, per-verb deadlines,
    exponential backoff.  Replaces the ad-hoc hardcoded deadline_s at
    each call site, so tightening the control-plane budget is one
    edit, not a grep."""
    global _CONTROL_POLICY
    from .rpc import CallPolicy, RPCClient

    if _CONTROL_POLICY is None:
        _CONTROL_POLICY = CallPolicy(
            timeout_s=2.0, deadline_s=5.0, attempts=3,
            backoff_base=0.05, backoff_cap=0.5,
            verb_deadlines={"stats": 2.0})
    cli = RPCClient(control_ep, timeout=_CONTROL_POLICY.timeout_s,
                    retries=1, retry_wait=0.05)
    try:
        return _CONTROL_POLICY.call(cli, verb, **kw)
    finally:
        cli.close()


def _start_pool_workers(cluster, router_ep, n, worker_opts, supervise,
                        make_restart_policy):
    """Process-mode serving pools (`--pool-mode process`): the
    supervisor spawns N pool-worker CHILDREN (serving/pool_worker.py),
    parses each one's READY line off the output pump, and attaches its
    endpoint to the router over the `attach_worker` control verb.  A
    worker that dies (SIGKILL chaos, OOM) is (a) reported to the
    router via `report_pool_death` so failover replay starts at the
    NEXT fabric step instead of burning the RPC deadline discovering
    it, and (b) respawned under the SAME _RestartPolicy budget the
    trainer/pserver children use — the fresh incarnation announces
    READY and re-attaches as a new pool.  Returns spawn_one so the
    elastic loop can grow the fleet through the same path."""
    from ..serving.pool_worker import READY_PREFIX

    lock = threading.Lock()
    state = {"next": 0}
    endpoints = {}  # tag -> latest incarnation's endpoint

    def spawn_one(reason="initial"):
        with lock:
            tag = "pool_worker.%d" % state["next"]
            state["next"] += 1
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        cmd = [sys.executable, "-u", "-m",
               "paddle_tpu.serving.pool_worker"] + list(worker_opts or [])
        sys.stderr.write("[launch] POOL WORKER SPAWN %s (%s)\n"
                         % (tag, reason))
        if supervise:
            cluster.supervise(tag, cmd, env, make_restart_policy())
        cluster.spawn(tag, cmd, env, aux=True)
        return tag

    prev_line = cluster.on_child_line

    def on_line(tag, line):
        if prev_line is not None:
            prev_line(tag, line)
        if not (tag.startswith("pool_worker.")
                and line.startswith(READY_PREFIX)):
            return
        ep = None
        for tok in line.split():
            if tok.startswith("endpoint="):
                ep = tok.split("=", 1)[1]
        if not ep:
            return
        with lock:
            endpoints[tag] = ep
        try:
            r = _control_call(router_ep, "attach_worker", endpoint=ep)
            sys.stderr.write("[launch] POOL WORKER ATTACHED %s %s "
                             "pid=%s\n" % (tag, ep, r.get("pid")))
        except Exception as e:
            sys.stderr.write("[launch] pool worker attach failed for "
                             "%s (%s): %r\n" % (tag, ep, e))

    cluster.on_child_line = on_line

    prev_death = cluster.on_child_death

    def on_death(tag, rc):
        if prev_death is not None:
            try:
                prev_death(tag, rc)
            except Exception:
                pass
        if not tag.startswith("pool_worker."):
            return
        with lock:
            ep = endpoints.pop(tag, None)
        if ep is None:
            return
        try:
            _control_call(router_ep, "report_pool_death", endpoint=ep)
        except Exception:
            pass  # the router's own RPC deadline still bounds detection

    cluster.on_child_death = on_death
    for _ in range(int(n)):
        spawn_one()
    return spawn_one


def _start_pool_elastic_loop(cluster, router_ep, min_pools, max_pools,
                             schedule, cooldown, stop_evt, policy,
                             nproc=2, spawn_worker=None):
    """Serving-pool loop of the UNIFIED supervisor (`--serve-pools
    MIN:MAX` against a `--serve-router` control endpoint): polls the
    FabricRouter's `stats` verb — the same verb shape the pserver axis
    polls — and applies grow/shrink through the router's `scale_pools`
    verb.  `--pool-schedule T:+N,T:-N` (seconds since start) replaces
    the observational policy with deterministic timed actions, the
    fabric's chaos/bench driver.  The policy instance is SHARED with
    the trainer and pserver axes: one cooldown, one action budget —
    three axes that cannot fight."""
    sched = []
    for spec in (schedule or "").split(","):
        spec = spec.strip()
        if spec:
            t_s, _, d = spec.partition(":")
            sched.append([float(t_s), int(d)])
    sched.sort(key=lambda e: e[0])
    scheduled_only = bool(sched)
    t_start = time.monotonic()

    def poll_stats():
        try:
            s = _control_call(router_ep, "stats")
            return s if isinstance(s, dict) else None
        except Exception:
            return None

    def scale(delta, reason):
        sys.stderr.write("[launch] ELASTIC POOL SCALE %+d (%s)\n"
                         % (delta, reason))
        try:
            if delta > 0 and spawn_worker is not None:
                # process mode: growth spawns supervised worker
                # children (their READY lines attach to the router);
                # shrink still flows through scale_pools — the router
                # drains and the retiring worker exits 0
                for _ in range(int(delta)):
                    spawn_worker(reason)
            else:
                _control_call(router_ep, "scale_pools",
                              delta=int(delta))
        except Exception as e:
            sys.stderr.write("[launch] pool scale failed: %r\n" % (e,))

    def loop():
        while not stop_evt.wait(0.5):
            if cluster is not None and (cluster._closing.is_set()
                                        or cluster.failed_rc is not None):
                return
            now = time.monotonic()
            if sched and now - t_start >= sched[0][0]:
                scale(sched.pop(0)[1], "scheduled")
                continue
            if scheduled_only:
                continue
            load = poll_stats()
            if load is None:
                continue
            act = policy.observe_pool_load(
                int(load.get("n_pools", 0)), load)
            if act is None:
                continue
            scale(+1 if act[0] == "grow_pool" else -1,
                  "policy: qd=%s occ=%s rej=%s"
                  % (load.get("queue_depth"), load.get("occupancy"),
                     load.get("rejected")))

    def run():
        try:
            loop()
        except Exception:
            import traceback

            sys.stderr.write("[launch] elastic pool loop died:\n")
            traceback.print_exc()

    threading.Thread(target=run, daemon=True,
                     name="elastic-pool-policy").start()


def launch_pserver(script_argv, nproc, n_pservers, base_env=None, sync=True,
                   chaos_kills=None, supervise=False, max_restarts=3,
                   restart_window=60.0, restart_backoff=0.5, ckpt_dir=None,
                   staleness_bound=None, elastic=None, elastic_schedule=None,
                   elastic_cooldown=3.0, elastic_pservers=None,
                   pserver_schedule=None, serve_router=None,
                   serve_pools=None, pool_schedule=None,
                   pool_mode="inproc", pool_worker_opts=None):
    if elastic_schedule and not elastic:
        # fail BEFORE any child spawns: a dropped schedule would run a
        # clean "no regression" job in which the membership trace under
        # test never happened
        raise ValueError(
            "--elastic-schedule requires --elastic MIN:MAX: the "
            "schedule drives the elastic machinery and alone would be "
            "silently ignored")
    if pserver_schedule and not elastic_pservers:
        raise ValueError(
            "--pserver-schedule requires --elastic-pservers MIN:MAX: "
            "the schedule drives the pserver-migration machinery and "
            "alone would be silently ignored")
    if serve_pools and not serve_router:
        raise ValueError(
            "--serve-pools MIN:MAX requires --serve-router ENDPOINT: "
            "the supervisor scales pools through the router's control "
            "verbs and has nowhere to send them")
    if pool_schedule and not serve_pools:
        raise ValueError(
            "--pool-schedule requires --serve-pools MIN:MAX: the "
            "schedule drives the fabric-scaling machinery and alone "
            "would be silently ignored")
    if pool_mode not in ("inproc", "process"):
        raise ValueError("--pool-mode must be inproc|process, got %r"
                         % (pool_mode,))
    if pool_mode == "process" and not serve_pools:
        raise ValueError(
            "--pool-mode process requires --serve-pools MIN:MAX: the "
            "supervisor owns the worker children and must know how "
            "many to spawn")
    min_pools = max_pools = None
    if serve_pools:
        min_pools, max_pools = (int(x)
                                for x in str(serve_pools).split(":"))
        if not (1 <= min_pools <= max_pools):
            raise ValueError(
                "--serve-pools MIN:MAX must satisfy 1 <= MIN <= MAX "
                "(got %s)" % serve_pools)
    min_ps = max_ps = None
    if elastic_pservers:
        min_ps, max_ps = (int(x) for x in str(elastic_pservers).split(":"))
        if not (1 <= min_ps <= n_pservers <= max_ps):
            raise ValueError(
                "--elastic-pservers MIN:MAX must satisfy MIN <= "
                "--pservers <= MAX (got %s with --pservers %d)"
                % (elastic_pservers, n_pservers))
    ports = [free_port() for _ in range(n_pservers)]
    # elastic pserver headroom: endpoints for growable servers are
    # reserved up front (the children aren't spawned until the policy
    # or schedule grows them); PADDLE_PSERVER_EPS stays the BASE list —
    # it defines the stable shard identity, never the live set
    spare_ports = [free_port()
                   for _ in range((max_ps or n_pservers) - n_pservers)]
    eps = ",".join("127.0.0.1:%d" % p for p in ports)
    common = dict(base_env or os.environ)
    common.update(
        PADDLE_PSERVER_EPS=eps,
        PADDLE_TRAINERS=str(nproc),
        DIST_SYNC_MODE="1" if sync else "0",
    )
    if staleness_bound is not None:
        # async bounded staleness: arm FLAGS_async_staleness_bound in
        # every child so pservers park trainers running ahead of the
        # slowest live peer (sync mode has the round barrier; the flag
        # is harmless there)
        common["FLAGS_async_staleness_bound"] = str(int(staleness_bound))
    if ckpt_dir:
        common["PADDLE_PSERVER_CKPT_DIR"] = ckpt_dir
    if supervise and not common.get("PADDLE_PSERVER_CKPT_DIR"):
        sys.stderr.write(
            "[launch] WARNING: --supervise without a checkpoint dir "
            "(--ckpt-dir / PADDLE_PSERVER_CKPT_DIR): a restarted pserver "
            "comes up COLD and the job's %s on that shard is lost\n"
            % ("optimizer state" if sync else
               "optimizer state AND async journal (updates since the "
               "last snapshot)"))

    def _policy():
        return _RestartPolicy(max_restarts=max_restarts,
                              window_s=restart_window,
                              backoff_s=restart_backoff)

    cluster = _Cluster()

    # trainer ids the launcher has seen die and NOT (yet) respawned: a
    # supervised pserver restart is re-briefed about them, because its
    # restored snapshot may predate the eviction (the ghost never
    # heartbeats the new incarnation, so liveness alone can't see it)
    dead_trainers = set()
    dead_lock = threading.Lock()

    def notify_trainer_death(tag, rc):
        """Tell every pserver a trainer child died (the `evict` verb): a
        trainer SIGKILLed before its first heartbeat was never tracked,
        so liveness eviction can't see it — but the LAUNCHER can, and
        the report unhangs any sync barrier waiting on the ghost while
        dropping its partial round contribution (unlike `complete`).
        When the supervisor will relaunch the child, the evict carries
        respawn=True so the pserver parks the id for readmission instead
        of declaring the job done — the death of the SOLE trainer must
        not take the pserver down under its booting replacement.
        Best-effort with short deadlines; re-evicting is a no-op."""
        if not tag.startswith("trainer."):
            return
        from .rpc import RPCClient

        tid = int(tag.split(".", 1)[1])
        respawning = _will_respawn(tag)
        with dead_lock:
            dead_trainers.add(tid)
        for ep in eps.split(","):
            cli = RPCClient(ep, timeout=2, retries=2, retry_wait=0.1)
            try:
                cli.call("evict", trainer_id=tid, deadline_s=5.0,
                         respawn=respawning)
            except Exception:
                pass  # pserver may be gone too; fail-fast handles that
            finally:
                cli.close()

    def _will_respawn(tag):
        """True when the supervisor is going to relaunch this child (it
        is registered for supervision and teardown hasn't started) —
        budget exhaustion later fails the whole cluster anyway, so a
        parked join on that path dies with everything else."""
        return (tag in cluster._supervised
                and not cluster._closing.is_set())

    cluster.on_child_death = notify_trainer_death

    def prepare_respawn(tag):
        """Supervisor pre-respawn hook.  For a dying TRAINER, pre-register
        its id on its behalf (runs AFTER the evict notification, BEFORE
        the respawn): the pserver readmits the id at the next round
        boundary and keeps the job alive while the replacement process
        boots — without this, the last survivor completing would declare
        the job done under the booting rejoiner.
        Returns False (skip the respawn) when every pserver says the job
        already finished.

        For a restarting PSERVER, re-briefs the new incarnation about
        trainers that are still dead: its restored snapshot may predate
        their eviction, and a ghost never heartbeats the new server, so
        without the report the restored barrier would wait on it
        forever."""
        from .rpc import RPCClient

        if tag.startswith("pserver."):
            idx = int(tag.split(".", 1)[1])
            ep = "127.0.0.1:%d" % ports[idx]

            def rebrief():
                if not _wait_port(ep, timeout=120):
                    return
                with dead_lock:
                    dead = sorted(dead_trainers)
                for tid in dead:
                    cli = RPCClient(ep, timeout=2, retries=3,
                                    retry_wait=0.1)
                    try:
                        cli.call("evict", trainer_id=tid, deadline_s=5.0,
                                 respawn=_will_respawn("trainer.%d" % tid))
                    except Exception:
                        pass
                    finally:
                        cli.close()

            threading.Thread(target=rebrief, daemon=True,
                             name="rebrief-%s" % tag).start()
            return True
        if not tag.startswith("trainer."):
            return True

        tid = int(tag.split(".", 1)[1])
        with dead_lock:
            dead_trainers.discard(tid)  # it is coming back
        admitted = reachable = 0
        for ep in eps.split(","):
            cli = RPCClient(ep, timeout=5, retries=3, retry_wait=0.1)
            try:
                # register() carries the stack-wide blocking budget
                # (barrier_timeout): a round boundary is cluster
                # progress, not network latency
                r = cli.register(trainer_id=tid)
                reachable += 1
                if isinstance(r, dict) and r.get("ok"):
                    admitted += 1
            except Exception:
                pass  # pserver down/restarting: its own recovery covers it
            finally:
                cli.close()
        # unreachable pservers don't veto the respawn — only an explicit
        # "done" consensus from every reachable one does
        return admitted > 0 or reachable == 0

    cluster.on_respawn = prepare_respawn

    def respawn_denied(tag, rc):
        """Restart-budget exhaustion is TERMINAL: the earlier death
        report promised a respawn (pservers parked the id as a pending
        join), but no replacement is coming — retract the promise with
        a respawn=False evict so survivors conclude NOW instead of
        serving a ghost until the eviction deadline (the whole cluster
        is about to fail-fast anyway; this makes the failure clean)."""
        if not tag.startswith("trainer."):
            return  # a failed pserver takes the cluster down fail-fast
        from .rpc import RPCClient

        tid = int(tag.split(".", 1)[1])
        for ep in eps.split(","):
            cli = RPCClient(ep, timeout=2, retries=2, retry_wait=0.1)
            try:
                cli.call("evict", trainer_id=tid, deadline_s=5.0,
                         respawn=False)
            except Exception:
                pass
            finally:
                cli.close()

    cluster.on_respawn_denied = respawn_denied
    for i, p in enumerate(ports):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="PSERVER",
            PADDLE_CURRENT_ENDPOINT="127.0.0.1:%d" % p,
        )
        cmd = [sys.executable, "-u"] + script_argv
        if supervise:
            # the respawn reuses the SAME endpoint + checkpoint env: the
            # restarted shard restores from its manifest checkpoint and
            # trainers re-fence on the incarnation bump
            cluster.supervise("pserver.%d" % i, cmd, env, _policy())
        cluster.spawn("pserver.%d" % i, cmd, env)
    for p in ports:
        if not _wait_port("127.0.0.1:%d" % p, cluster=cluster):
            sys.stderr.write("[launch] pserver port %d never opened\n" % p)
            # snapshot BEFORE kill(): the launcher's own SIGKILL of healthy
            # peers (-9) must not mask the original crash code
            dead = [pr.poll() for _, pr, _ in cluster.procs
                    if pr.poll() is not None]
            cluster.kill()
            bad = [rc for rc in dead if rc != 0]
            return bad[0] if bad else 1
    for rank in range(nproc):
        env = dict(common)
        env.update(
            PADDLE_TRAINING_ROLE="TRAINER",
            PADDLE_TRAINER_ID=str(rank),
        )
        cmd = [sys.executable, "-u"] + script_argv
        if supervise:
            # a relaunched trainer is a fresh process: the launcher's
            # death report evicted the ghost first (_pump ordering), the
            # replacement re-registers and is readmitted at the next
            # round boundary (elastic rejoin)
            cluster.supervise("trainer.%d" % rank, cmd, env, _policy())
        cluster.spawn("trainer.%d" % rank, cmd, env)
    stop_elastic = threading.Event()
    # ONE policy instance spans every armed elastic axis (trainers,
    # pservers, serving pools): the cooldown and the action budget are
    # shared, so a trainer grow/shrink, a pserver shard migration, and
    # a pool scale cannot fire in the same window — one membership
    # change at a time, as the damping promises
    shared_policy = None
    n_axes = sum(1 for x in (elastic, elastic_pservers, serve_pools)
                 if x)
    if n_axes >= 2:
        emin, emax = ((int(x) for x in str(elastic).split(":"))
                      if elastic else (1, max(1, nproc)))
        shared_policy = _ScalingPolicy(
            emin, emax, cooldown_s=elastic_cooldown,
            min_ps=min_ps, max_ps=max_ps,
            min_pools=min_pools, max_pools=max_pools)
    if elastic:
        _start_elastic_loop(cluster, common, script_argv, nproc, elastic,
                            elastic_schedule, elastic_cooldown,
                            supervise, _policy, stop_elastic,
                            policy=shared_policy)
    if elastic_pservers:
        base_tags = [("pserver.%d" % i, "127.0.0.1:%d" % p)
                     for i, p in enumerate(ports)]
        spare = [("pserver.%d" % (n_pservers + i), "127.0.0.1:%d" % p)
                 for i, p in enumerate(spare_ports)]
        _start_pserver_elastic_loop(
            cluster, common, script_argv, base_tags, spare, min_ps,
            max_ps, pserver_schedule, elastic_cooldown, supervise,
            _policy, stop_elastic, nproc, policy=shared_policy)
    if serve_pools:
        pool_policy = shared_policy or _ScalingPolicy(
            1, max(1, nproc), cooldown_s=elastic_cooldown,
            min_pools=min_pools, max_pools=max_pools)
        spawn_worker = None
        if pool_mode == "process":
            spawn_worker = _start_pool_workers(
                cluster, serve_router, min_pools, pool_worker_opts,
                supervise, _policy)
        _start_pool_elastic_loop(
            cluster, serve_router, min_pools, max_pools, pool_schedule,
            elastic_cooldown, stop_elastic, pool_policy, nproc,
            spawn_worker=spawn_worker)
    _arm_chaos(cluster, chaos_kills)
    try:
        return cluster.wait()
    finally:
        stop_elastic.set()


def _start_elastic_loop(cluster, common, script_argv, nproc, elastic,
                        elastic_schedule, elastic_cooldown, supervise,
                        make_restart_policy, stop_evt, policy=None):
    """The scaling-policy loop (`--elastic MIN:MAX`): a supervisor
    thread watches per-trainer STEP progress off the output pump and
    adds/retires trainer children — the pserver admits/evicts them at
    round boundaries and mints plan epochs, trainers re-derive their
    plans (docs/FAULT_TOLERANCE.md "Elastic autoscaling").

    `elastic_schedule` ("T:+N,T:-N", seconds since start) replaces the
    observational policy with deterministic timed actions — the
    bench/chaos driver, riding the exact same grow/shrink machinery.
    Retirement picks the highest-rank live trainer: it is SIGKILLed as
    an expected failure after being dropped from supervision, so the
    death notification reports it as terminal (respawn=False) and the
    pserver evicts for good instead of parking a rejoin."""
    min_t, max_t = (int(x) for x in str(elastic).split(":"))
    if policy is None:
        policy = _ScalingPolicy(min_t, max_t, cooldown_s=elastic_cooldown)
    schedule = []
    for spec in (elastic_schedule or "").split(","):
        spec = spec.strip()
        if spec:
            t_s, _, d = spec.partition(":")
            schedule.append([float(t_s), int(d)])
    schedule.sort(key=lambda e: e[0])
    scheduled_only = bool(schedule)
    step_seen = {}  # tag -> recent STEP wall times
    seen_lock = threading.Lock()

    def on_line(tag, line):
        if tag.startswith("trainer.") and line.startswith("STEP "):
            with seen_lock:
                step_seen.setdefault(tag, []).append(time.monotonic())

    cluster.on_child_line = on_line
    t_start = time.monotonic()
    next_rank = [nproc]

    def live_trainers():
        with cluster._lock:
            procs = list(cluster.procs)
        latest = {}
        completed = False
        for tag, p, _ in procs:
            if tag.startswith("trainer."):
                latest[tag] = p  # latest incarnation wins
        live = {}
        for tag, p in latest.items():
            if p.poll() is None:
                live[tag] = p
            elif p.returncode == 0:
                completed = True
        return live, completed

    def grow(reason):
        rank = next_rank[0]
        next_rank[0] += 1
        tag = "trainer.%d" % rank
        env = dict(common, PADDLE_TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(rank))
        cmd = [sys.executable, "-u"] + script_argv
        sys.stderr.write("[launch] ELASTIC GROW %s (%s)\n" % (tag, reason))
        if supervise:
            cluster.supervise(tag, cmd, env, make_restart_policy())
        cluster.spawn(tag, cmd, env)

    def shrink(tag, reason):
        sys.stderr.write("[launch] ELASTIC SHRINK %s (%s)\n"
                         % (tag, reason))
        cluster.unsupervise(tag)  # terminal: the evict must not park
        cluster.kill_one(tag)

    def loop():
        window = max(2.0, 2.0 * float(elastic_cooldown))
        while not stop_evt.wait(0.5):
            if cluster._closing.is_set() or cluster.failed_rc is not None:
                return
            live, completed = live_trainers()
            if completed:
                # the job is winding down: no more actions — and a
                # grown trainer that never made a step is booting into
                # a cluster whose pservers may exit under it (it would
                # crash-loop on register); retire it cleanly
                with seen_lock:
                    for tag in list(live):
                        if not step_seen.get(tag):
                            shrink(tag, "job completed before it joined")
                return
            now = time.monotonic()
            if schedule and now - t_start >= schedule[0][0]:
                delta = schedule.pop(0)[1]
                if delta > 0:
                    for _ in range(min(delta, max_t - len(live))):
                        grow("scheduled")
                else:
                    victims = sorted(
                        live, key=lambda t: -int(t.split(".", 1)[1]))
                    for tag in victims[:min(-delta,
                                            len(live) - min_t)]:
                        shrink(tag, "scheduled")
                continue
            if scheduled_only:
                # deterministic driver: actions come only from the
                # schedule — but the loop must OUTLIVE it, or the
                # winddown branch above (retiring a grown trainer that
                # never joined before the job completed) is unreachable
                # for schedules ending in a grow
                continue
            with seen_lock:
                rates = {}
                for tag in live:
                    ts = [t for t in step_seen.get(tag, [])
                          if now - t <= window]
                    step_seen[tag] = ts
                    # pace over the tag's OWN observed span, not the
                    # full window: a freshly-grown trainer with a few
                    # steps at full speed must not read as a straggler
                    # just because it booted mid-window.  Under 3 steps
                    # the pace is unknown (None): the tag can be
                    # neither a straggler nor a grow justification —
                    # which also keeps the policy from stacking a
                    # second grow while the last one is still booting.
                    span = ts[-1] - ts[0] if len(ts) >= 3 else 0.0
                    rates[tag] = ((len(ts) - 1) / span if span > 0
                                  else None)
            act = policy.decide(set(live), rates)
            if act is None:
                continue
            if act[0] == "grow":
                grow("policy")
            else:
                shrink(act[1], "policy")

    def run():
        try:
            loop()
        except Exception:
            # a dead policy thread must at least say so: silently losing
            # elasticity mid-job is the failure mode this log line exists
            # to catch
            import traceback

            sys.stderr.write("[launch] elastic policy loop died:\n")
            traceback.print_exc()

    threading.Thread(target=run, daemon=True,
                     name="elastic-policy").start()


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn a local training cluster with the PADDLE_* env contract",
    )
    parser.add_argument("--nproc", type=int, default=2, help="trainer count")
    parser.add_argument(
        "--mode", choices=("collective", "pserver"), default="collective"
    )
    parser.add_argument(
        "--pservers", type=int, default=None,
        help="pserver count: defaults to 2 in pserver mode and 0 in "
        "collective mode (pass a count there for HYBRID jobs — sparse "
        "embedding traffic rides the pservers, dense grads the mesh)"
    )
    parser.add_argument(
        "--async-mode", action="store_true",
        help="pserver mode: async updates (no barriers)",
    )
    parser.add_argument(
        "--chaos-kill", action="append", default=[], metavar="TAG:SECONDS",
        help="fault injection: SIGKILL child TAG (e.g. trainer.1) after "
        "SECONDS; the kill is an expected failure — the run succeeds if "
        "the survivors finish (repeatable)",
    )
    parser.add_argument(
        "--supervise", action="store_true",
        help="pserver mode: relaunch children that die nonzero — a "
        "restarted pserver restores its checkpoint (trainers re-fence on "
        "the incarnation bump), a restarted trainer re-registers and "
        "rejoins at a round boundary (docs/FAULT_TOLERANCE.md)",
    )
    parser.add_argument(
        "--max-restarts", type=int, default=3,
        help="supervised restart budget per child within --restart-window "
        "seconds (exhausting it makes the next death a real failure)",
    )
    parser.add_argument(
        "--restart-window", type=float, default=60.0,
        help="sliding window (seconds) for the --max-restarts budget",
    )
    parser.add_argument(
        "--restart-backoff", type=float, default=0.5,
        help="base supervised-restart backoff in seconds (doubles per "
        "restart within the window)",
    )
    parser.add_argument(
        "--ckpt-dir", default=None,
        help="pserver mode: sets PADDLE_PSERVER_CKPT_DIR for the "
        "children so supervised pserver restarts restore instead of "
        "starting cold (async mode also homes the write-ahead journal "
        "here — without it an async restart loses updates since the "
        "last snapshot)",
    )
    parser.add_argument(
        "--elastic", default=None, metavar="MIN:MAX",
        help="pserver mode: elastic autoscaling — a supervisor policy "
        "loop watches per-trainer step progress and adds (up to MAX) or "
        "retires (down to MIN) trainer children; the pservers admit/"
        "evict them at round boundaries, mint plan epochs, and trainers "
        "re-derive their comm plans for the new world size "
        "(docs/FAULT_TOLERANCE.md).  Usually combined with --supervise",
    )
    parser.add_argument(
        "--elastic-schedule", default=None, metavar="T:+N,T:-N",
        help="deterministic elastic driver: at T seconds after launch, "
        "grow (+N) or shrink (-N) the trainer fleet through the same "
        "machinery the policy loop uses (bench/chaos harness; replaces "
        "the observational policy)",
    )
    parser.add_argument(
        "--elastic-cooldown", type=float, default=3.0, metavar="SECONDS",
        help="minimum seconds between elastic policy actions (flap "
        "damping; the policy also rides a per-window action budget)",
    )
    parser.add_argument(
        "--elastic-pservers", default=None, metavar="MIN:MAX",
        help="pserver mode: elastic PSERVER set — the supervisor polls "
        "each server's load (queue depth / staleness parks) and grows a "
        "fresh empty pserver or retires one, driving the two-phase "
        "journaled shard migration (migrate_begin/commit) so shard "
        "state MOVES with the membership and the plan epoch flips "
        "trainer dispatch atomically (docs/FAULT_TOLERANCE.md 'Live "
        "shard migration')",
    )
    parser.add_argument(
        "--pserver-schedule", default=None, metavar="T:+N,T:-N",
        help="deterministic pserver-migration driver: at T seconds "
        "after launch, grow (+N) or retire (-N) pservers through the "
        "same migration machinery the load policy uses (bench/chaos "
        "harness)",
    )
    parser.add_argument(
        "--serve-router", default=None, metavar="HOST:PORT",
        help="serving-fabric control endpoint (a FabricRouter's "
        "serve_control server): the supervisor polls its `stats` verb "
        "— the same shape the pserver axis polls — and scales pools "
        "through `scale_pools`, making serving the THIRD axis of the "
        "one shared policy/budget (docs/SERVING.md 'Serving fabric')",
    )
    parser.add_argument(
        "--serve-pools", default=None, metavar="MIN:MAX",
        help="elastic serving-pool bounds against --serve-router: grow "
        "on fabric pressure (queue depth / occupancy / rejections), "
        "drain-and-retire on sustained idleness, sharing ONE cooldown "
        "and action budget with the trainer and pserver axes",
    )
    parser.add_argument(
        "--pool-schedule", default=None, metavar="T:+N,T:-N",
        help="deterministic serving-pool driver: at T seconds after "
        "launch, add (+N) or drain (-N) pools through the same router "
        "verbs the load policy uses (fabric bench/chaos harness)",
    )
    parser.add_argument(
        "--pool-mode", default="inproc", choices=("inproc", "process"),
        help="process: the supervisor spawns pool-worker CHILDREN "
        "(serving/pool_worker.py) and attaches each READY endpoint to "
        "the --serve-router fabric; a dead worker is death-reported "
        "and respawned under the shared restart budget (--supervise)",
    )
    parser.add_argument(
        "--pool-worker-opts", default="", metavar="ARGS",
        help="extra argv passed through to every spawned pool worker "
        "(--pool-mode process), e.g. '--hp {...} --n-slots 2'",
    )
    parser.add_argument(
        "--staleness-bound", type=int, default=None, metavar="STEPS",
        help="async pserver mode: arm FLAGS_async_staleness_bound in "
        "every child — pservers park pushes/prefetches from a trainer "
        "running more than STEPS ahead of the slowest live peer "
        "(eviction/completion frees the bound)",
    )
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    chaos_kills = []
    for spec in args.chaos_kill:
        tag, _, after = spec.rpartition(":")
        try:
            after_s = float(after)
        except ValueError:
            tag = ""
        if not tag:
            parser.error("--chaos-kill wants TAG:SECONDS, got %r" % spec)
        chaos_kills.append((tag, after_s))

    script_argv = [args.script] + args.script_args
    base_env = None
    if args.mode == "collective" and (args.elastic or args.elastic_schedule):
        # elastic collective (docs/FAULT_TOLERANCE.md "Elastic
        # autoscaling", collective mode): a SINGLE-process virtual-device
        # mesh re-traces on resize — the trainer drains its ordered-io
        # tokens, rebuilds the shard_map over the new dp mesh, and
        # rescales host-side like the pserver path.  Multi-process
        # meshes still need a relaunch (one device per process is
        # pinned at jax.distributed init).
        if args.nproc != 1:
            parser.error(
                "--elastic with --mode collective needs --nproc 1: the "
                "elastic mesh resizes VIRTUAL devices inside one "
                "process (multi-process meshes pin one device per "
                "process at jax.distributed init — relaunch to resize)")
        if not args.elastic:
            parser.error("--elastic-schedule requires --elastic MIN:MAX")
        base_env = dict(os.environ)
        base_env["DIST_COLLECTIVE_ELASTIC"] = args.elastic
        if args.elastic_schedule:
            base_env["DIST_COLLECTIVE_SCHEDULE"] = args.elastic_schedule
    if args.mode == "collective":
        rc = launch_collective(script_argv, args.nproc,
                               base_env=base_env,
                               chaos_kills=chaos_kills,
                               n_pservers=args.pservers or 0)
    else:
        rc = launch_pserver(
            script_argv, args.nproc,
            args.pservers if args.pservers is not None else 2,
            sync=not args.async_mode,
            chaos_kills=chaos_kills, supervise=args.supervise,
            max_restarts=args.max_restarts,
            restart_window=args.restart_window,
            restart_backoff=args.restart_backoff, ckpt_dir=args.ckpt_dir,
            staleness_bound=args.staleness_bound,
            elastic=args.elastic, elastic_schedule=args.elastic_schedule,
            elastic_cooldown=args.elastic_cooldown,
            elastic_pservers=args.elastic_pservers,
            pserver_schedule=args.pserver_schedule,
            serve_router=args.serve_router,
            serve_pools=args.serve_pools,
            pool_schedule=args.pool_schedule,
            pool_mode=args.pool_mode,
            pool_worker_opts=shlex.split(args.pool_worker_opts),
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
