"""Parameter-server runtime: the `listen_and_serv` service loop.

TPU-native re-design of the reference pserver
(operators/distributed_ops/listen_and_serv_op.cc — RunSyncLoop :106,
RunAsyncLoop :216): a host-side service that owns a scope of parameter /
optimizer-state *blocks* (1-D slices of the original variables, see the
distribute transpiler) and applies optimizer shard programs built by the
transpiler.  Each shard program is a tiny Program compiled once by the
regular Executor (compile-first, like everything else) — the pserver's
"optimize sub-blocks" of the reference become cached XLA CPU executables.

Sync mode round protocol (reference barrier semantics):
  1. every live trainer sends its grad blocks, then barrier("send")
  2. when all send-barriers arrive: grads are summed per block, the lr
     program (decay schedule) runs once, then every shard program runs
  3. trainers issue get() for updated param blocks, then barrier("fetch")
  4. round resets
Async mode: each send applies its shard program immediately, gets are
served from the live scope, no barriers.
"""

import threading

import numpy as np

from .. import framework
from ..core.scope import Scope


class ParameterServer:
    """Service object plugged into rpc.VarServer."""

    def __init__(
        self,
        shard_programs,
        grad_to_shard,
        lr_program=None,
        num_trainers=1,
        sync_mode=True,
        scope=None,
        sparse_tables=None,
        sparse_lr=0.01,
        checkpoint_dir=None,
        checkpoint_every=1,
        server_idx=0,
    ):
        from ..executor import Executor
        from ..places import CPUPlace

        self.shard_programs = shard_programs  # list[Program]
        self.grad_to_shard = grad_to_shard  # grad block name -> shard idx
        self.lr_program = lr_program
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self.scope = scope if scope is not None else Scope()
        self.exe = Executor(CPUPlace())
        # sparse embedding shards: shard name -> dict with "tbl" (2-D
        # np.ndarray), "lr" (constant fallback), "opt" ({type, attrs,
        # lr_name, lr_scale}) and lazily-created slot state (moment*,
        # beta*_pow).  Rows here belong to this server (global row g ->
        # server g%N at local index g//N); id routing is client-side, we
        # see local ids.  Legacy (tbl, lr) tuples are normalized.
        self.sparse_tables = {
            k: (v if isinstance(v, dict) else {"tbl": v[0], "lr": v[1]})
            for k, v in dict(sparse_tables or {}).items()
        }
        self.sparse_lr = sparse_lr  # fallback for tables without own lr
        # sync mode queues sparse grads and applies them at round time,
        # AFTER the lr_program run — exactly the reference's
        # optimizer-sub-block-at-barrier semantics (async applies on
        # arrival with the current lr)
        self._pending_sparse = []

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # async mode: run the lr (decay) program once per logical trainer
        # step, not once per grad-var send — trigger it on a single
        # designated grad so a k-param model doesn't advance the schedule's
        # step counter k times per step
        self._lr_trigger = min(grad_to_shard) if grad_to_shard else None
        self._pending = {}  # grad block name -> {trainer_id: np.ndarray}
        self._send_barriers = set()
        self._fetch_barriers = set()
        self._round = 0  # bumped after each optimize step
        self._params_ready = not sync_mode
        self._live_trainers = num_trainers
        self._done = threading.Event()
        # shard checkpointing (go/pserver/service.go:346 Checkpoint +
        # LoadCheckpoint :175 capability): periodic atomic snapshots of the
        # shard scope + sparse tables, restored on restart
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.server_idx = int(server_idx)
        self._async_sends = 0
        self._ckpt_write_lock = threading.Lock()  # serialize writer threads

    # ---- checkpoint (fault tolerance) -----------------------------------
    def _ckpt_path(self, dir=None):
        import os

        return os.path.join(
            dir or self.checkpoint_dir, "pserver_%d.ckpt" % self.server_idx
        )

    def _snapshot(self):
        """Copy shard state (called under the service lock; numpy copies so
        later in-place updates can't tear the snapshot)."""
        return {
            "round": self._round,
            "vars": {
                n: np.array(self.scope.get(n))
                for n in self.scope.local_var_names()
            },
            "sparse": {
                k: {
                    kk: (np.array(vv) if isinstance(vv, np.ndarray) else vv)
                    for kk, vv in info.items()
                    if kk == "tbl"
                    or kk.startswith(("moment", "beta", "velocity"))
                }
                for k, info in self.sparse_tables.items()
            },
        }

    def _write_snapshot(self, data, dir=None):
        """Atomic write-tmp + rename (the Go pserver's crc+rename
        discipline); runs OFF the service lock.  `dir` overrides the
        server's own checkpoint_dir for trainer-requested snapshots."""
        import os
        import pickle

        target = dir or self.checkpoint_dir
        os.makedirs(target, exist_ok=True)
        path = self._ckpt_path(dir=target)
        tmp = path + ".tmp"
        with self._ckpt_write_lock:
            with open(tmp, "wb") as f:
                pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)

    def save_checkpoint(self, dir=None):
        if not (dir or self.checkpoint_dir):
            return False
        self._write_snapshot(self._snapshot(), dir=dir)
        return True

    def load_checkpoint(self):
        """Restore shard state from the latest snapshot; returns the
        restored round or None when no checkpoint exists."""
        if not self.checkpoint_dir:
            return None
        import os
        import pickle

        path = self._ckpt_path()
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            data = pickle.load(f)
        for n, v in data["vars"].items():
            self.scope.set(n, v)
        for k, v in data["sparse"].items():
            if k not in self.sparse_tables:
                continue
            info = self.sparse_tables[k]
            if isinstance(v, dict):  # current format: tbl + slot state
                for kk, vv in v.items():
                    info[kk] = (np.ascontiguousarray(vv)
                                if isinstance(vv, np.ndarray) else vv)
            else:  # legacy checkpoint: bare table array
                info["tbl"] = np.ascontiguousarray(v)
        self._round = int(data.get("round", 0))
        return self._round

    def _maybe_checkpoint(self):
        """Called under the service lock: snapshot cheaply here, serialize
        + write on a background thread so trainer RPCs never stall on disk."""
        if not (self.checkpoint_dir and self._round % self.checkpoint_every == 0):
            return
        try:
            data = self._snapshot()
        except Exception:
            import traceback

            traceback.print_exc()
            return

        def write():
            try:
                self._write_snapshot(data)
            except Exception:
                import traceback

                traceback.print_exc()

        threading.Thread(target=write, daemon=True).start()

    # ---- verb dispatch ---------------------------------------------------
    def handle(self, verb, **kw):
        try:
            return getattr(self, "_h_" + verb)(**kw)
        except Exception as e:  # ship errors to the client
            import traceback

            return {"__error__": "%s\n%s" % (e, traceback.format_exc())}

    # ---- optimize --------------------------------------------------------
    def _apply_shard(self, shard_idx, feed):
        prog = self.shard_programs[shard_idx]
        self.exe.run(prog, feed=feed, fetch_list=[], scope=self.scope)

    def _run_round(self):
        """All send-barriers in: sum grads, run lr + all shard programs
        + the queued sparse updates (after lr, so a scheduled lr is this
        round's decayed value — the order the local program runs in)."""
        if self.lr_program is not None:
            self.exe.run(self.lr_program, feed={}, fetch_list=[], scope=self.scope)
        for gname, per_trainer in sorted(self._pending.items()):
            total = None
            for v in per_trainer.values():
                total = v if total is None else total + v
            self._apply_shard(self.grad_to_shard[gname], {gname: total})
        by_table = {}
        for t, ids, rows in self._pending_sparse:
            by_table.setdefault(t, []).append((ids, rows))
        for t, chunks in sorted(by_table.items()):
            self._apply_sparse(
                t,
                np.concatenate([c[0] for c in chunks]),
                np.concatenate([c[1] for c in chunks], axis=0),
                advance_pows=False,
            )
        self._pending_sparse = []
        # per-round state that advances even on ROWLESS rounds: the
        # local op runs every step regardless of which rows a shard's id
        # hashing happened to receive — adam beta pows advance
        # (ops/optimizer_ops.py Beta1PowOut) and momentum velocity
        # decays (the densified SparseMomentumFunctor covers every row)
        for t, info in sorted(self.sparse_tables.items()):
            self._advance_pows(info)
            if t not in by_table and (
                    (info.get("opt") or {}).get("type") == "momentum"):
                self._apply_sparse(t, np.zeros((0,), np.int64),
                                   np.zeros((0, info["tbl"].shape[1]),
                                            info["tbl"].dtype),
                                   advance_pows=False)
        self._pending.clear()
        self._send_barriers.clear()
        self._params_ready = True
        self._round += 1
        self._maybe_checkpoint()
        self._cv.notify_all()

    # ---- handlers --------------------------------------------------------
    def _h_send(self, name, value, trainer_id=0):
        value = np.asarray(value)
        if not self.sync_mode:
            with self._lock:
                if self.lr_program is not None and name == self._lr_trigger:
                    self.exe.run(
                        self.lr_program, feed={}, fetch_list=[], scope=self.scope
                    )
                self._apply_shard(self.grad_to_shard[name], {name: value})
                self._async_sends += 1
                if (
                    self.checkpoint_dir
                    and self._async_sends
                    % (self.checkpoint_every * max(1, len(self.grad_to_shard)))
                    == 0
                ):
                    self._round += 1
                    self._maybe_checkpoint()
            return {"ok": True}
        with self._lock:
            self._pending.setdefault(name, {})[trainer_id] = value
        return {"ok": True}

    def _h_barrier(self, kind, trainer_id=0):
        if not self.sync_mode:
            return {"ok": True}
        with self._cv:
            if kind == "send":
                self._send_barriers.add(trainer_id)
                if len(self._send_barriers) >= self._live_trainers:
                    self._run_round()
                else:
                    rnd = self._round
                    self._cv.wait_for(
                        lambda: self._round > rnd or self._done.is_set()
                    )
            elif kind == "fetch":
                self._fetch_barriers.add(trainer_id)
                if len(self._fetch_barriers) >= self._live_trainers:
                    self._fetch_barriers.clear()
                    self._params_ready = False
                    self._cv.notify_all()
        return {"ok": True}

    def _h_get(self, name, trainer_id=0):
        if self.sync_mode:
            with self._cv:
                self._cv.wait_for(
                    lambda: self._params_ready or self._done.is_set()
                )
        var = self.scope.find_var(name)
        if var is None:
            raise KeyError("pserver has no var %s" % name)
        return np.asarray(var)

    # ---- sparse embedding shards (distributed lookup table) -------------
    def _h_prefetch(self, table, ids, trainer_id=0):
        """Serve embedding rows by local row id (prefetch_op analog)."""
        tbl = self.sparse_tables[table]["tbl"]
        ids = np.asarray(ids).reshape(-1)
        ids = np.clip(ids, 0, tbl.shape[0] - 1)
        with self._lock:
            return tbl[ids].copy()

    def _sparse_lr_value(self, info):
        """Current learning rate for a sparse table: the scheduled lr var
        from the pserver scope (decayed by lr_program) when named, else
        the captured constant, else the server-wide fallback.  A
        SCHEDULED lr (named var, no constant) whose var is missing is an
        error — silently training at a stale constant is the failure the
        old NotImplementedError guard existed to prevent."""
        opt = info.get("opt") or {}
        name = opt.get("lr_name")
        if name:
            var = self.scope.find_var(name)
            if var is not None:
                return (float(np.asarray(var).reshape(-1)[0])
                        * float(opt.get("lr_scale", 1.0)))
            if info.get("lr") is None:
                raise RuntimeError(
                    "sparse table optimizer needs scheduled lr var %r but "
                    "the pserver scope does not hold it (lr_program split "
                    "miss?) and no constant fallback was captured" % name)
        if info.get("lr") is not None:
            return float(info["lr"])
        return float(self.sparse_lr)

    def _advance_pows(self, info):
        """Advance an adam table's beta pows by one step (no-op for
        non-adam tables or before the first application created them)."""
        opt = info.get("opt") or {}
        if opt.get("type") != "adam":
            return
        at = opt.get("attrs") or {}
        b1 = float(at.get("beta1", 0.9))
        b2 = float(at.get("beta2", 0.999))
        info["beta1_pow"] = info.get("beta1_pow", b1) * b1
        info["beta2_pow"] = info.get("beta2_pow", b2) * b2

    def _apply_sparse(self, table, ids, rows, advance_pows=True):
        """One optimizer application on this shard's touched rows
        (SelectedRows semantics: duplicates merged first — the moment
        updates are non-linear in g).  Mirrors the lazy/sparse branches
        of ops/optimizer_ops.py so a dist run matches the local
        is_sparse run row for row.  Called under self._lock.
        advance_pows=False defers the adam beta-pow advance to the
        caller (sync rounds advance once per round for EVERY table via
        _advance_pows, even row-less ones)."""
        info = self.sparse_tables[table]
        tbl = info["tbl"]
        opt = info.get("opt") or {}
        typ = opt.get("type", "sgd")
        at = opt.get("attrs") or {}
        ids = np.asarray(ids).reshape(-1)
        # explicit second dim: -1 is ambiguous (ValueError) for 0 rows,
        # and rowless momentum decay feeds exactly that
        rows = np.asarray(rows, dtype=tbl.dtype).reshape(
            ids.size, tbl.shape[1])
        uids, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((uids.size, tbl.shape[1]), tbl.dtype)
        np.add.at(g, inv, rows)
        lr = self._sparse_lr_value(info)
        if typ == "sgd":
            tbl[uids] -= lr * g
        elif typ == "adagrad":
            eps = float(at.get("epsilon", 1e-6))
            m = info.setdefault("moment", np.zeros_like(tbl))
            mn = m[uids] + g * g
            m[uids] = mn
            tbl[uids] -= lr * g / (np.sqrt(mn) + eps)
        elif typ == "momentum":
            # momentum_op.h SparseMomentumFunctor: densified rule over
            # EVERY shard row — untouched rows' velocity still decays
            mu = float(at.get("mu", 0.9))
            v = info.setdefault("velocity", np.zeros_like(tbl))
            g_dense = np.zeros_like(tbl)
            g_dense[uids] = g
            v *= mu
            v += g_dense
            if at.get("use_nesterov"):
                tbl -= lr * (g_dense + mu * v)
            else:
                tbl -= lr * v
        elif typ == "adam":
            b1 = float(at.get("beta1", 0.9))
            b2 = float(at.get("beta2", 0.999))
            eps = float(at.get("epsilon", 1e-8))
            m1 = info.setdefault("moment1", np.zeros_like(tbl))
            m2 = info.setdefault("moment2", np.zeros_like(tbl))
            b1p = info.setdefault("beta1_pow", b1)
            b2p = info.setdefault("beta2_pow", b2)
            lr_t = lr * np.sqrt(1.0 - b2p) / (1.0 - b1p)
            m1n = b1 * m1[uids] + (1.0 - b1) * g
            m2n = b2 * m2[uids] + (1.0 - b2) * g * g
            m1[uids], m2[uids] = m1n, m2n
            tbl[uids] -= lr_t * m1n / (np.sqrt(m2n) + eps)
            if advance_pows:
                # async mode: global beta pows advance per application
                # (the lazy adam rule, adam_op.h SelectedRows branch)
                info["beta1_pow"] = b1p * b1
                info["beta2_pow"] = b2p * b2
        else:
            raise ValueError("unknown sparse optimizer %r" % typ)

    def _h_send_sparse(self, table, ids, rows, trainer_id=0):
        """Sparse optimizer update on this server's rows (SelectedRows
        grad).  Sync mode queues until the round barrier so the update
        sees this round's scheduled lr and all trainers' rows merge into
        ONE application (the reference's optimizer-sub-block-at-barrier
        semantics); async applies immediately."""
        ids = np.asarray(ids).reshape(-1)
        rows = np.asarray(rows)
        with self._lock:
            if self.sync_mode:
                self._pending_sparse.append((table, ids, rows))
            else:
                self._apply_sparse(table, ids, rows)
        return {"ok": True}

    def _h_checkpoint_notify(self, dir=None, trainer_id=0):
        """Trainer-initiated checkpoint (checkpoint_notify_op.cc analog).
        Snapshots into the REQUESTED dir without adopting it — the
        server's own periodic checkpoints keep their configured home, so
        they never overwrite (or resurrect) a trainer serial dir."""
        with self._lock:
            ok = self.save_checkpoint(dir=dir)
        return {"ok": bool(ok), "round": self._round}

    def _h_complete(self, trainer_id=0):
        with self._cv:
            self._live_trainers -= 1
            if self._live_trainers <= 0:
                self._done.set()
            # a departing trainer may unblock a pending round
            if (
                self.sync_mode
                and self._live_trainers > 0
                and len(self._send_barriers) >= self._live_trainers
            ):
                self._run_round()
            self._cv.notify_all()
        return {"ok": True}

    def wait_done(self, timeout=None):
        return self._done.wait(timeout)


def run_pserver(program, scope, executor=None):
    """Execute a transpiled pserver program: start the VarServer on the
    listen_and_serv op's endpoint, block until all trainers complete.

    This is what Executor.run does when it sees a `listen_and_serv` op —
    the analog of ListenAndServOp::RunImpl.
    """
    from .rpc import make_var_server

    listen_op = None
    for op in program.global_block().ops:
        if op.type == "listen_and_serv":
            listen_op = op
            break
    assert listen_op is not None, "no listen_and_serv op in pserver program"
    a = listen_op.attrs

    shard_programs = [framework.Program.from_json(s) for s in a["optimize_programs"]]
    lr_program = (
        framework.Program.from_json(a["lr_program"]) if a.get("lr_program") else None
    )

    # materialize block vars from the full vars the startup program created
    for src, block_name, begin, end in a["slice_plan"]:
        var = scope.find_var(src)
        if var is None:
            raise RuntimeError(
                "pserver startup did not create %s (run get_startup_program "
                "through this executor first)" % src
            )
        flat = np.asarray(var).reshape(-1)
        scope.set(block_name, np.ascontiguousarray(flat[begin:end]))
    for name in a.get("whole_vars", []):
        if scope.find_var(name) is None:
            raise RuntimeError("pserver startup did not create %s" % name)

    # distributed lookup-table shards: slice this server's rows (g%N) out
    # of the full table the startup program initialized.  Spec row:
    # [shard, src, server_idx, n_servers, lr] (+ optional opt dict)
    sparse_tables = {}
    for spec in a.get("sparse_tables", []):
        shard_name, src, server_idx, n_servers, lr = spec[:5]
        opt = spec[5] if len(spec) > 5 else None
        var = scope.find_var(src)
        if var is None:
            raise RuntimeError(
                "pserver startup did not create lookup table %s" % src
            )
        full = np.array(var)
        sparse_tables[shard_name] = {
            "tbl": np.ascontiguousarray(full[int(server_idx)::int(n_servers)]),
            "lr": float(lr) if lr is not None else None,
            "opt": dict(opt) if opt else {"type": "sgd", "attrs": {}},
        }

    import os as _os

    # checkpoint wiring: attr from the transpiler config, else the
    # PADDLE_PSERVER_CKPT_DIR env contract (test/ops harness)
    ckpt_dir = a.get("checkpoint_dir") or _os.environ.get(
        "PADDLE_PSERVER_CKPT_DIR"
    )
    ckpt_every = int(
        a.get("checkpoint_every")
        or _os.environ.get("PADDLE_PSERVER_CKPT_EVERY", 1)
    )
    try:
        server_idx = [s.strip() for s in _os.environ.get(
            "PADDLE_PSERVER_EPS", ""
        ).split(",")].index(a["endpoint"])
    except ValueError:
        server_idx = 0

    service = ParameterServer(
        shard_programs,
        dict(a["grad_to_shard"]),
        lr_program=lr_program,
        num_trainers=int(a["trainers"]),
        sync_mode=bool(a["sync_mode"]),
        scope=scope,
        sparse_tables=sparse_tables,
        sparse_lr=float(a.get("sparse_lr", 0.01)),
        checkpoint_dir=ckpt_dir,
        checkpoint_every=ckpt_every,
        server_idx=server_idx,
    )
    restored = service.load_checkpoint()
    if restored is not None:
        print("PSERVER RESTORED round=%d" % restored, flush=True)
    server = make_var_server(a["endpoint"], service).start()
    try:
        service.wait_done()
    finally:
        server.shutdown()
