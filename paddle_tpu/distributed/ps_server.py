"""Parameter-server runtime: the `listen_and_serv` service loop.

TPU-native re-design of the reference pserver
(operators/distributed_ops/listen_and_serv_op.cc — RunSyncLoop :106,
RunAsyncLoop :216): a host-side service that owns a scope of parameter /
optimizer-state *blocks* (1-D slices of the original variables, see the
distribute transpiler) and applies optimizer shard programs built by the
transpiler.  Each shard program is a tiny Program compiled once by the
regular Executor (compile-first, like everything else) — the pserver's
"optimize sub-blocks" of the reference become cached XLA CPU executables.

Sync mode round protocol (reference barrier semantics):
  1. every live trainer sends its grad blocks, then barrier("send");
     each arrival folds into a running per-grad partial sum immediately
     (overlapped with the wire — the round holds no summation loop)
  2. when all send-barriers arrive: the lr program (decay schedule) runs
     once, then the folded sums apply through ONE jitted fused call per
     optimizer group (fused_apply.py; unfusable shards keep their
     per-block executor programs)
  3. trainers issue get() for updated param blocks, then barrier("fetch")
  4. round resets
Async mode: each send applies its shard program immediately, gets are
served from the live scope, no barriers.  Durability and ordering come
from the ASYNC layer instead (docs/FAULT_TOLERANCE.md, "Durable async
sparse"): every applied sparse chunk / dense bucket is appended to a
crc-framed fsync'd write-ahead journal BEFORE its ack (rotated at each
snapshot; a restarted incarnation replays journal-after-snapshot and
loses zero applied updates, skipping a torn tail record cold);
per-sender sequence fences (_sparse_fence monotonic, _dense_fence
contiguous+ahead-set for the pipelined window) turn the client's
at-least-once re-delivery into exactly-once application across SIGKILL;
and FLAGS_async_staleness_bound parks pushes/prefetches from a trainer
running ahead of the slowest live peer until it catches up or departs.

Fault tolerance (docs/FAULT_TOLERANCE.md):
  * liveness — trainers send a ``heartbeat`` verb from a background
    sender (rpc.ensure_heartbeat); a heartbeat-TRACKED trainer that goes
    silent past FLAGS_eviction_deadline is evicted: removed from the
    live set, its unsummed grads and queued sparse rows dropped, and any
    pending barrier re-evaluates against the survivors so the round
    completes instead of deadlocking.  Trainers that never heartbeat are
    never evicted (exactly the pre-liveness behavior), and eviction runs
    in SYNC mode — plus ASYNC mode when a staleness bound is armed,
    where a dead laggard would otherwise park every fast peer forever.
  * checkpoints — atomic tmp+rename snapshots plus a crc-carrying
    manifest; a torn or corrupt snapshot is skipped on restart, never a
    crash.

Async-mode sparse slot-state approximation (ADVICE r5): tables touched
by a send advance their adam beta-pows per APPLICATION (the lazy-adam
rule); tables receiving no rows between two lr-trigger sends advance
pows / decay momentum velocity once per trigger so an unlucky shard
cannot stall forever.  The residual gap vs the sync schedule: touched
tables advance per-application rather than per-step, each trainer's own
trigger fires the catch-up (so N async trainers advance untouched
tables ~N times per global step), and a pure-sparse model (no dense
grad, hence no lr trigger) keeps the legacy per-application-only rule.
With bucketed comm (FLAGS_comm_bucket_bytes) and comm_inflight > 1, an
async step spanning several buckets per endpoint may also interleave
the lr-trigger bucket with another bucket's applications — arrival
order across the in-flight window is free.  Sync mode is unaffected:
its application order comes from the round barrier, not arrival.
"""

import struct
import threading

import numpy as np

from .. import framework
from ..core.scope import Scope

# write-ahead journal record framing (async mode, docs/FAULT_TOLERANCE.md):
# [8B big-endian payload length][4B crc32][pickled record].  A record is
# appended + fsync'd BEFORE the apply's reply leaves the server, so an
# acked update is durable by construction; a kill mid-append leaves a
# truncated/corrupt TAIL that restore skips cold (counted), exactly like
# a corrupt snapshot — the unacked update is re-shipped by the client.
_J_HEAD = struct.Struct(">QI")
# cap a single journal record's claimed length (corrupt headers must not
# allocate gigabytes); generous vs any real chunk/bucket
_J_MAX_RECORD = 1 << 31
# pure-sparse async streams never bump the dense round counter, so the
# journal would grow unbounded between snapshots: force a snapshot (and
# with it a journal rotation) every this many appended records
_J_ROTATE_RECORDS = 512


class ParameterServer:
    """Service object plugged into rpc.VarServer."""

    def __init__(
        self,
        shard_programs,
        grad_to_shard,
        lr_program=None,
        num_trainers=1,
        sync_mode=True,
        scope=None,
        sparse_tables=None,
        sparse_lr=0.01,
        checkpoint_dir=None,
        checkpoint_every=1,
        server_idx=0,
        eviction_deadline=None,
        staleness_bound=None,
        plan_spec=None,
        endpoint=None,
        ps_world=None,
        sparse_shard_idx=None,
    ):
        from ..executor import Executor
        from ..places import CPUPlace

        self.shard_programs = shard_programs  # list[Program]
        self.grad_to_shard = grad_to_shard  # grad block name -> shard idx
        self.lr_program = lr_program
        self.num_trainers = num_trainers
        self.sync_mode = sync_mode
        self.scope = scope if scope is not None else Scope()
        self.exe = Executor(CPUPlace())
        # sparse embedding shards: shard name -> dict with "tbl" (2-D
        # np.ndarray), "lr" (constant fallback), "opt" ({type, attrs,
        # lr_name, lr_scale}) and lazily-created slot state (moment*,
        # beta*_pow).  Rows here belong to this server (global row g ->
        # server g%N at local index g//N); id routing is client-side, we
        # see local ids.  Legacy (tbl, lr) tuples are normalized.
        self.sparse_tables = {
            k: (v if isinstance(v, dict) else {"tbl": v[0], "lr": v[1]})
            for k, v in dict(sparse_tables or {}).items()
        }
        self.sparse_lr = sparse_lr  # fallback for tables without own lr
        # sync mode queues sparse grads and applies them at round time,
        # AFTER the lr_program run — exactly the reference's
        # optimizer-sub-block-at-barrier semantics (async applies on
        # arrival with the current lr).  Keyed (trainer_id, table) so a
        # fenced replay after a pserver restart overwrites rather than
        # double-queues (each trainer ships at most one chunk per table
        # per step — see ops/dist_ops.py _send_sparse)
        self._pending_sparse = {}

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # async mode: run the lr (decay) program once per logical trainer
        # step, not once per grad-var send — trigger it on a single
        # designated grad so a k-param model doesn't advance the schedule's
        # step counter k times per step
        self._lr_trigger = min(grad_to_shard) if grad_to_shard else None
        self._pending = {}  # grad block name -> {trainer_id: np.ndarray}
        # incremental fold: each trainer's dense contribution is added
        # into a running per-grad partial sum AT ARRIVAL (overlapped with
        # the wire) so _run_round no longer sums per-trainer temps while
        # holding the round lock.  _pending stays the authoritative
        # per-trainer record — overwrites (fenced replays) and evictions
        # rebuild the affected partials from it, in arrival order, so
        # the fold is bit-identical to the old round-time sum.
        self._partial = {}  # grad block name -> running sum ndarray
        # jitted fused optimize path (fused_apply.py), built lazily at
        # the first round so in-process tests with stub shard programs
        # never pay (or crash on) the analysis
        self._fused = None
        self._fused_ready = False
        self._send_barriers = set()
        self._fetch_barriers = set()
        # folded-barrier bookkeeping (bucketed wire path): how many of a
        # trainer's declared per-step buckets this server has seen
        self._send_bucket_counts = {}  # trainer_id -> buckets this round
        self._fetch_bucket_counts = {}
        # incarnation-fenced stream bookkeeping (docs/FAULT_TOLERANCE.md):
        # buckets carrying a (step, seq_idx) pair are counted by SET so a
        # fenced replay after a pserver restart is idempotent — a
        # re-delivered bucket overwrites its keyed pending slot and cannot
        # advance the fold count twice.  _folded_send/_folded_fetch record
        # the last step token each trainer FOLDED; they ride the
        # checkpoint snapshot, so after a restore they fence exactly the
        # rounds the restored params already contain (replays of those
        # rounds are dropped, in-flight rounds are re-assembled).
        self._send_step = {}     # tid -> step token being assembled
        self._send_seen = {}     # tid -> set of seq_idx seen for that step
        self._fetch_step = {}
        self._fetch_seen = {}
        self._folded_send = {}   # tid -> last folded send step (ckpt'd)
        self._folded_fetch = {}  # tid -> last folded fetch step (ckpt'd)
        self._pending_joins = set()  # tids waiting for a round boundary
        self._round = 0  # bumped after each optimize step
        self._params_ready = not sync_mode
        # liveness: the explicit live set replaces the old bare count so
        # eviction can target ONE trainer's pending state.  _tracked maps
        # heartbeat-reporting trainers to their last-contact time; only
        # tracked trainers are ever evicted (no heartbeats => the exact
        # pre-liveness behavior, nothing times out).
        self._live = set(range(num_trainers))
        self._tracked = {}  # trainer_id -> time.monotonic() of last contact
        self._evicted = set()
        self._completed = set()  # clean departures (dedups repeat completes)
        if eviction_deadline is None:
            from ..flags import get_flag

            eviction_deadline = float(get_flag("eviction_deadline"))
        self.eviction_deadline = max(0.1, float(eviction_deadline))
        self._reaper = None
        # async mode: sparse tables touched since the last lr-trigger send
        # (per-step catch-up for rowless shards, see module docstring)
        self._async_touched = set()
        self._done = threading.Event()
        # shard checkpointing (go/pserver/service.go:346 Checkpoint +
        # LoadCheckpoint :175 capability): periodic atomic snapshots of the
        # shard scope + sparse tables, restored on restart
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.server_idx = int(server_idx)
        self._async_sends = 0
        self._ckpt_write_lock = threading.Lock()  # serialize writer threads
        # async crash consistency (docs/FAULT_TOLERANCE.md, async section):
        # a write-ahead journal of applied updates makes the async stream
        # replayable across SIGKILL, and per-sender sequence fences make
        # the client's at-least-once re-delivery exactly-once.
        #   _sparse_fence[(tid, table)] -> highest seq durably applied
        #     (sends are serial per trainer, so monotonic drop-if-<= is
        #     exact; gaps are legal — rowless/empty chunks are acked but
        #     not journaled, so a restored fence can sit below the
        #     client's ack high-water without breaking dedup)
        #   _dense_fence[tid] -> [contiguous fence, set of applied aseqs
        #     above it] — async dense buckets ride the pipelined window
        #     and may arrive out of order
        # Both fences ride the checkpoint snapshot AND are rebuilt by
        # journal replay, so a re-shipped chunk is dropped whether the
        # original apply landed in the snapshot or only in the journal.
        self._sparse_fence = {}
        self._dense_fence = {}
        # bounded staleness: per-trainer logical clocks derived from the
        # seq tokens; a push/prefetch from a trainer more than
        # _staleness_bound ahead of the slowest LIVE peer parks on _cv
        # until the laggard catches up or departs
        self._trainer_clock = {}
        if staleness_bound is None:
            from ..flags import get_flag

            staleness_bound = get_flag("async_staleness_bound")
        self._staleness_bound = int(staleness_bound)
        from ..flags import get_flag as _gf

        self._journal_on = bool(_gf("async_journal"))
        self._journal_seg = 0  # current segment id (rotated per snapshot)
        self._journal_f = None
        self._journal_err = False  # first append failure warns loudly once
        self._replaying = False  # journal replay must not re-journal
        self._j_recs_at_snap = 0
        self._sends_at_ckpt = 0  # dense cadence marker (post-journal)
        # stale-writer guard: two snapshot writers can land out of order;
        # an older round must never overwrite a newer snapshot (its
        # journal segments may already be deleted)
        self._ckpt_written_round = -1
        # recovery observability (bench / smoke COUNTERS evidence)
        self.counters = {"evictions": 0, "readmissions": 0,
                         "registrations": 0, "dup_round_drops": 0,
                         "lost_rounds": 0,
                         # async durability + staleness evidence
                         "dedup_drops": 0, "journal_records": 0,
                         "journal_bytes": 0, "journal_replayed": 0,
                         "journal_tail_skips": 0, "staleness_parks": 0,
                         "staleness_timeouts": 0, "parked_ms": 0.0,
                         # elastic autoscaling evidence
                         "plan_epochs": 0, "stale_plan_drops": 0}
        # elastic autoscaling (docs/FAULT_TOLERANCE.md "Elastic
        # autoscaling"): the plan epoch is bumped — at a ROUND BOUNDARY
        # in sync mode, never mid-assembly — whenever the live set
        # changes durably (eviction, admission, clean departure), so
        # trainers re-derive their comm plan for the new world and
        # stale-epoch frames are fenced like stale incarnations.  The
        # membership phase log feeds the "steps/s tracks the trainer
        # count" bench evidence.
        self._plan_epoch = 0
        self._plan_dirty = False
        import time as _time

        self._phases = []  # closed phases: {epoch, world, rounds, wall_s}
        self._phase = {"epoch": 0, "world": len(self._live),
                       "round0": 0, "t0": _time.monotonic()}
        # ---- live pserver shard migration (docs/FAULT_TOLERANCE.md
        # "Live shard migration"): the declarative plan spec lets this
        # server re-derive shard->endpoint dispatch for a changed pserver
        # world and compute which of ITS shards must move.  The handoff
        # is two-phase (migrate_begin freezes + serializes + ships to the
        # targets, which journal/fsync BEFORE acking; migrate_commit
        # adopts the new world, drops the moved state, and mints the plan
        # epoch) so the epoch provably never mints before target
        # durability — a SIGKILL of source or target mid-handoff leaves
        # the OLD assignment authoritative and loses zero applied
        # updates.
        self.plan_spec = plan_spec
        self.endpoint = endpoint
        self._ps_world = [str(e) for e in (
            ps_world or (plan_spec or {}).get("endpoints")
            or ([endpoint] if endpoint else []))]
        # sparse shard name -> BASE shard index (rows hash g % n_base;
        # the index is the shard's stable identity across migrations)
        self._sparse_shard_idx = dict(sparse_shard_idx or {})
        self._frozen = False
        self._mig = None      # in-flight migrate_begin capture
        self._mig_gen = 0     # generation: a timed-out freeze self-aborts
        # delta handoff (migrate_begin delta=True): shard -> set of row
        # ids dirtied since the UNFROZEN snapshot shipped (None value =
        # whole-table mutation, re-ship everything); None when inactive
        self._mig_dirty = None
        # adopted-state registry: shard programs / sparse specs /
        # lr_program this server acquired via migrate_in — they must ride
        # the snapshot, because a restarted server rebuilds everything
        # else from its (transpile-time) listen_and_serv attrs
        self._adopted = {"programs": {}, "sparse": {}, "lr_program": None,
                         "dropped": []}
        self._dropped_vars = set()  # migrated-away param-block var names
        # runtime-surfaced reduced-guarantee flag (the legacy per-var
        # async path is journaled but UNFENCED): set on first such apply
        self._unfenced_async = False
        self.counters.update({
            "migrations_out": 0, "migrations_in": 0, "migrate_aborts": 0,
            "migrated_bytes_out": 0, "migrated_bytes_in": 0,
            "migrated_shards_out": 0, "migrated_shards_in": 0})
        # every pserver start — cold or restored — is a new INCARNATION;
        # the number rides every rpc reply envelope so trainers can fence
        # a restart (see rpc.py incarnation registry)
        self.incarnation = self._mint_incarnation()

    def _mint_incarnation(self):
        """Monotonic per-start incarnation: a counter persisted next to
        the checkpoint when there is a durable home, else time-derived
        (still distinct across restarts).  Best-effort — fencing needs
        the number to CHANGE per start, nothing stronger."""
        import os
        import time

        if self.checkpoint_dir:
            try:
                os.makedirs(self.checkpoint_dir, exist_ok=True)
                path = os.path.join(
                    self.checkpoint_dir,
                    "pserver_%d.incarnation" % self.server_idx)
                prev = 0
                if os.path.exists(path):
                    with open(path) as f:
                        prev = int(f.read().strip() or 0)
                inc = prev + 1
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(str(inc))
                os.replace(tmp, path)
                return inc
            except (OSError, ValueError):
                pass
        return int(time.time() * 1000) & 0x7FFFFFFFFFFF

    # ---- async write-ahead journal (durable async sparse) ----------------
    def _journal_enabled(self):
        return bool(self._journal_on and self.checkpoint_dir
                    and not self.sync_mode)

    def _journal_path(self, seg):
        import os

        return os.path.join(
            self.checkpoint_dir,
            "pserver_%d.journal.seg%06d" % (self.server_idx, int(seg)))

    def _journal_segments(self):
        """Existing segment ids for this shard, sorted ascending."""
        import os
        import re

        if not self.checkpoint_dir:
            return []
        pat = re.compile(
            r"^pserver_%d\.journal\.seg(\d+)$" % self.server_idx)
        try:
            names = os.listdir(self.checkpoint_dir)
        except OSError:
            return []
        return sorted(int(m.group(1))
                      for m in (pat.match(n) for n in names) if m)

    def _journal_append_locked(self, rec):
        """Append one crc-framed record and fsync — called under the
        service lock, BEFORE the apply's reply leaves, so an acked update
        is durable.  A disk failure degrades to the old lose-on-restart
        behavior, loudly (once), rather than killing the serving loop.

        Known tradeoff: the fsync runs under the service lock, so every
        concurrent verb (reads included) stalls behind each disk sync.
        Group commit — append+flush under the lock, fsync the captured
        file object outside it before the reply — would lift that, but
        interacts with snapshot-capture rotation closing the file
        mid-sync; left as future perf work (the apply itself already
        serializes writers here)."""
        if not self._journal_enabled() or self._replaying:
            return
        import os
        import pickle
        import sys
        import zlib

        try:
            payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            frame = _J_HEAD.pack(len(payload),
                                 zlib.crc32(payload) & 0xFFFFFFFF) + payload
            if self._journal_f is None:
                os.makedirs(self.checkpoint_dir, exist_ok=True)
                self._journal_f = open(
                    self._journal_path(self._journal_seg), "ab")
            self._journal_f.write(frame)
            self._journal_f.flush()
            os.fsync(self._journal_f.fileno())
            self.counters["journal_records"] += 1
            self.counters["journal_bytes"] += len(frame)
            self._journal_err = False
        except OSError as e:
            if not self._journal_err:
                self._journal_err = True
                sys.stderr.write(
                    "PSERVER journal append failed (%s): async updates "
                    "since the last snapshot are NOT crash-durable until "
                    "the journal recovers\n" % e)

    def _journal_quarantine(self):
        """An UNUSABLE snapshot orphans its journal: the segments hold
        deltas whose base state is gone, so they can never be replayed
        correctly — and left on disk they would poison the NEXT lineage
        (the fresh writer would append into / a later restore would
        replay dead-lineage records on top of new state).  Remove them,
        loudly, and reseed the writer past their numbering."""
        import os
        import sys

        if not self._journal_enabled():
            return
        segs = self._journal_segments()
        if not segs:
            return
        sys.stderr.write(
            "PSERVER journal segments %s belong to the unusable "
            "snapshot's lineage (deltas without their base); removing "
            "them — the cold start cannot replay them\n" % segs)
        self.counters["journal_tail_skips"] += len(segs)
        for seg in segs:
            try:
                os.remove(self._journal_path(seg))
            except OSError:
                pass
        self._journal_seg = max(self._journal_seg, segs[-1] + 1)

    def _journal_rotate_locked(self):
        """Start a fresh segment (at snapshot capture): everything before
        the new segment is contained in the snapshot being taken, so once
        that snapshot lands the older segments can be deleted.  Returns
        the new segment id (the snapshot's replay-from marker)."""
        if not self._journal_enabled():
            return None
        if self._journal_f is not None:
            try:
                self._journal_f.close()
            except OSError:
                pass
            self._journal_f = None
        self._journal_seg += 1
        self._j_recs_at_snap = self.counters["journal_records"]
        return self._journal_seg

    def _journal_maybe_snapshot_locked(self):
        """Sparse-only async streams never bump the dense round counter,
        so without this the journal would grow unbounded between
        snapshots: force a snapshot (and its rotation) every
        _J_ROTATE_RECORDS appended records."""
        if (self.checkpoint_dir and not self._replaying
                and self.counters["journal_records"]
                - self._j_recs_at_snap >= _J_ROTATE_RECORDS):
            self._round += 1
            self._maybe_checkpoint()

    def _replay_journal(self, from_seg):
        """Apply journal records from segment `from_seg` on, in order,
        through the SAME application paths the live verbs use (lr
        triggers, slot state, fences and clocks all advance identically).
        A corrupt/truncated record ends ITS segment's replay (counted,
        cold — the kill landed mid-append and the unacked update will be
        re-shipped); later segments, written by later incarnations, still
        replay.  New appends then go to a segment PAST everything seen,
        so a skipped tail is never appended after."""
        if not self._journal_enabled():
            return 0
        import pickle
        import sys
        import zlib

        segs = [s for s in self._journal_segments() if s >= int(from_seg)]
        n = 0
        self._replaying = True
        try:
            for seg in segs:
                try:
                    with open(self._journal_path(seg), "rb") as f:
                        buf = f.read()
                except OSError as e:
                    sys.stderr.write(
                        "PSERVER journal seg %d unreadable (%s); "
                        "skipped\n" % (seg, e))
                    self.counters["journal_tail_skips"] += 1
                    continue
                off = 0
                while off < len(buf):
                    if off + _J_HEAD.size > len(buf):
                        self.counters["journal_tail_skips"] += 1
                        break
                    ln, crc = _J_HEAD.unpack_from(buf, off)
                    if (ln > _J_MAX_RECORD
                            or off + _J_HEAD.size + ln > len(buf)):
                        self.counters["journal_tail_skips"] += 1
                        break
                    payload = buf[off + _J_HEAD.size:
                                  off + _J_HEAD.size + ln]
                    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                        self.counters["journal_tail_skips"] += 1
                        break
                    try:
                        rec = pickle.loads(payload)
                        self._apply_journal_record(rec)
                    except Exception as e:
                        sys.stderr.write(
                            "PSERVER journal seg %d record unusable (%s); "
                            "skipping segment tail\n" % (seg, e))
                        self.counters["journal_tail_skips"] += 1
                        break
                    n += 1
                    off += _J_HEAD.size + ln
            # new appends must land in a segment future restores WILL
            # replay: past every segment seen, and never below the
            # snapshot's replay-from marker — after a snapshot that
            # deleted all covered segments, an empty journal dir must
            # not reset the writer to seg 0 (records there would sit
            # below the marker and a second restart would skip them,
            # silently losing acked updates)
            existing = self._journal_segments()
            self._journal_seg = max(
                [self._journal_seg, int(from_seg)]
                + [s + 1 for s in existing])
        finally:
            self._replaying = False
        self.counters["journal_replayed"] = n
        if n or self.counters["journal_tail_skips"]:
            print("PSERVER JOURNAL-REPLAY records=%d tail_skips=%d "
                  "segments=%s" % (n, self.counters["journal_tail_skips"],
                                   segs), flush=True)
        return n

    def _apply_journal_record(self, rec):
        kind = rec.get("k")
        tid = int(rec.get("tid", 0))
        if kind == "s":
            table = rec["t"]
            if table not in self.sparse_tables:
                import sys

                sys.stderr.write(
                    "PSERVER journal names unknown sparse table %r; "
                    "record skipped\n" % (table,))
                return
            ids = np.asarray(rec["i"])
            if ids.size:
                self._async_touched.add(table)
                self._apply_sparse(table, ids, np.asarray(rec["r"]))
            if rec.get("q") is not None:
                key = (tid, table)
                seq = int(rec["q"])
                self._sparse_fence[key] = max(
                    self._sparse_fence.get(key, 0), seq)
                self._clock_update_locked(tid, seq)
        elif kind == "d":
            aseq = rec.get("q")
            if aseq is not None and self._dense_fence_is_dup(tid, aseq):
                return
            for name in sorted(rec["b"]):
                self._apply_async_send_locked(name,
                                              np.asarray(rec["b"][name]))
            if aseq is not None:
                # aseq stays OUT of _trainer_clock (bucket units, not
                # steps — see _h_send_bucket)
                self._dense_fence_commit(tid, aseq)
        elif kind == "v":
            self._apply_async_send_locked(rec["n"], np.asarray(rec["v"]))
        # ---- live shard migration records (docs/FAULT_TOLERANCE.md
        # "Live shard migration"): state HANDED OFF from another server,
        # applied both live (migrate_in) and from journal replay — an
        # adopted shard survives the target's own SIGKILL either way
        elif kind == "mshard":
            g = str(rec["g"])
            prog = framework.Program.from_json(rec["prog"])
            si = self.grad_to_shard.get(g)
            if si is None:
                self.grad_to_shard[g] = len(self.shard_programs)
                self.shard_programs.append(prog)
            else:
                self.shard_programs[si] = prog  # idempotent retry
            for n, v in sorted(rec["vars"].items()):
                self.scope.set(n, np.ascontiguousarray(v))
                # a shard can move BACK (2 -> 3 -> 2): re-adoption
                # clears the dropped-var fence for its vars
                self._dropped_vars.discard(n)
            if g in self._adopted["dropped"]:
                self._adopted["dropped"].remove(g)
            self._adopted["programs"][g] = rec["prog"]
            self._fused = None
            self._fused_ready = False
            self._recalc_lr_trigger_locked()
        elif kind == "mtable":
            shard = str(rec["t"])
            if (self._mig_dirty is not None
                    and shard in self._mig_dirty):
                # a full table landed UNDER our own in-flight delta
                # handoff of the same shard (shard bouncing back):
                # row-level tracking is no longer sound — re-ship whole
                self._mig_dirty[shard] = None
            info = {}
            for kk, vv in rec["info"].items():
                info[kk] = (np.ascontiguousarray(vv)
                            if isinstance(vv, np.ndarray) else vv)
            info.setdefault("opt", {"type": "sgd", "attrs": {}})
            self.sparse_tables[shard] = info
            if shard in self._adopted["dropped"]:
                self._adopted["dropped"].remove(shard)
            if int(rec.get("s", -1)) >= 0:
                self._sparse_shard_idx[shard] = int(rec["s"])
            for t, sq in (rec.get("fences") or {}).items():
                key = (int(t), shard)
                self._sparse_fence[key] = max(
                    self._sparse_fence.get(key, 0), int(sq))
            self._adopted["sparse"][shard] = {
                "s": int(rec.get("s", -1)),
                "lr": info.get("lr"), "opt": info.get("opt")}
        elif kind == "mwhole":
            for n, v in sorted((rec.get("vars") or {}).items()):
                # set-if-absent: an established server's own copies (its
                # lr decay state advanced by its own rounds) win
                if self.scope.find_var(n) is None:
                    self.scope.set(n, np.ascontiguousarray(v))
            if rec.get("lr_program") and self.lr_program is None:
                self.lr_program = framework.Program.from_json(
                    rec["lr_program"])
                self._adopted["lr_program"] = rec["lr_program"]
        elif kind == "mrows":
            # delta-handoff FINAL TAIL: row-level overwrite of a table
            # whose full snapshot already landed (an earlier mtable
            # record in this handoff) — ids carry the rows dirtied
            # while the source kept serving, `scal` the non-row state
            # (adam beta pows, lr) whose final frozen values win
            shard = str(rec["t"])
            info = self.sparse_tables.get(shard)
            if info is None:
                import sys

                sys.stderr.write(
                    "PSERVER mrows names unknown sparse table %r "
                    "(no snapshot landed first); record skipped\n"
                    % (shard,))
                return
            ids = np.asarray(rec["i"]).reshape(-1).astype(np.int64)
            for kk, vv in sorted((rec.get("rows") or {}).items()):
                vv = np.asarray(vv)
                arr = info.get(kk)
                if arr is None:
                    # a moment/velocity slot first materialized AFTER
                    # the snapshot (setdefault in _apply_sparse)
                    arr = info[kk] = np.zeros_like(info["tbl"])
                if ids.size:
                    arr[ids] = vv
            for kk, vv in sorted((rec.get("scal") or {}).items()):
                info[kk] = (np.ascontiguousarray(vv)
                            if isinstance(vv, np.ndarray) else vv)
            for t, sq in (rec.get("fences") or {}).items():
                key = (int(t), shard)
                self._sparse_fence[key] = max(
                    self._sparse_fence.get(key, 0), int(sq))
        elif kind == "mfence":
            # migrated fold fences: rounds the shipped state already
            # contains must fence here exactly as at the source (sync
            # rounds are lockstep, so max-merge is exact)
            for t, s in (rec.get("send") or {}).items():
                t = int(t)
                self._folded_send[t] = max(
                    self._folded_send.get(t, -1), int(s))
            for t, s in (rec.get("fetch") or {}).items():
                t = int(t)
                self._folded_fetch[t] = max(
                    self._folded_fetch.get(t, -1), int(s))

    # ---- async delivery fences + bounded staleness -----------------------
    def _dense_fence_is_dup(self, tid, aseq):
        st = self._dense_fence.get(int(tid))
        if st is None or aseq is None:
            return False
        aseq = int(aseq)
        return aseq <= st[0] or aseq in st[1]

    def _dense_fence_commit(self, tid, aseq):
        """Contiguous fence + ahead-set: async dense buckets ride the
        pipelined window, so they may commit out of order — the fence
        advances through the set as the gaps fill, keeping the set no
        larger than the in-flight window."""
        st = self._dense_fence.setdefault(int(tid), [0, set()])
        st[1].add(int(aseq))
        while st[0] + 1 in st[1]:
            st[0] += 1
            st[1].discard(st[0])

    def _clock_update_locked(self, tid, clock):
        tid = int(tid)
        cur = self._trainer_clock.get(tid, 0)
        if int(clock) > cur:
            self._trainer_clock[tid] = int(clock)
            if not self._replaying:
                self._cv.notify_all()

    def _park_if_stale_locked(self, tid, clock):
        """Bounded staleness (async mode): hold this push/prefetch while
        its trainer runs more than _staleness_bound steps ahead of the
        slowest LIVE peer; released when the laggard's clock advances or
        it departs (complete / eviction — which is why the reaper also
        runs on async servers when the bound is armed).  The wait is
        capped: a bound must throttle, never deadlock — on timeout the
        call proceeds loudly and the timeout is counted."""
        bound = self._staleness_bound
        if bound <= 0 or self.sync_mode or self._replaying or clock is None:
            return
        import time

        tid = int(tid)
        clock = int(clock)

        def clear():
            if (self._done.is_set() or tid in self._evicted
                    or tid not in self._live):
                return True
            others = [c for t, c in self._trainer_clock.items()
                      if t != tid and t in self._live]
            return not others or clock - min(others) <= bound

        if clear():
            return
        self.counters["staleness_parks"] += 1
        print("PSERVER PARK trainer=%d clock=%d bound=%d"
              % (tid, clock, bound), flush=True)
        t0 = time.monotonic()
        limit = max(10.0, 3.0 * self.eviction_deadline)
        released = self._cv.wait_for(clear, timeout=limit)
        self.counters["parked_ms"] = round(
            self.counters["parked_ms"]
            + (time.monotonic() - t0) * 1e3, 3)
        if not released:
            self.counters["staleness_timeouts"] += 1
            print("PSERVER STALENESS-TIMEOUT trainer=%d clock=%d: laggard "
                  "made no progress in %.0fs; releasing the park rather "
                  "than deadlocking" % (tid, clock, limit), flush=True)

    # ---- checkpoint (fault tolerance) -----------------------------------
    def _ckpt_path(self, dir=None):
        import os

        return os.path.join(
            dir or self.checkpoint_dir, "pserver_%d.ckpt" % self.server_idx
        )

    def _snapshot(self):
        """Copy shard state (called under the service lock; numpy copies so
        later in-place updates can't tear the snapshot)."""
        return {
            "round": self._round,
            # async delivery fences + clocks ride the snapshot like the
            # sync fold fences do: a restored server must drop re-shipped
            # chunks whose applies are INSIDE the restored state
            "async_seq": {
                "sparse": dict(self._sparse_fence),
                "dense": {t: [st[0], sorted(st[1])]
                          for t, st in self._dense_fence.items()},
                "clock": dict(self._trainer_clock)},
            # journal rotation: records before this segment are contained
            # in THIS snapshot; restore replays segments >= it, and the
            # writer deletes segments < it once the snapshot lands
            "journal_seg": self._journal_rotate_locked(),
            # the plan epoch rides the snapshot: a restored server must
            # not fall behind its trainers' epochs (its stale fence
            # would misread every current-epoch frame as the future)
            "plan": {"epoch": self._plan_epoch},
            # live shard migration: the current pserver world plus every
            # shard program / sparse spec ADOPTED via migrate_in — a
            # restarted server rebuilds everything else from its
            # transpile-time listen_and_serv attrs, but adopted shards
            # exist only here (and in the journal), and dropped shards
            # must not be resurrected from those same attrs
            "migration": {
                "world": list(self._ps_world),
                "programs": dict(self._adopted["programs"]),
                "sparse": {k: dict(v) for k, v in
                           self._adopted["sparse"].items()},
                "lr_program": self._adopted["lr_program"],
                "dropped": list(self._adopted["dropped"]),
                "dropped_vars": sorted(self._dropped_vars),
                "shard_idx": dict(self._sparse_shard_idx)},
            # per-trainer fold fences ride the SAME snapshot as the
            # params: after a restore, replayed buckets for rounds the
            # restored state already contains are dropped, rounds the
            # snapshot missed are re-assembled (incarnation fencing)
            "folded": {"send": dict(self._folded_send),
                       "fetch": dict(self._folded_fetch)},
            # departed trainers ride the snapshot too: a restored sync
            # server must not rebuild its live set around ghosts it
            # already evicted — their folds would never arrive and every
            # restored barrier would hang (register still readmits them).
            # The LIVE set rides as well: an elastic-grown rank (>= the
            # transpile-time trainer count) is otherwise forgotten by a
            # restart's range(num_trainers) reconstruction, and the
            # restored server would declare the job done under it the
            # moment the original ranks complete
            "departed": {"evicted": sorted(self._evicted),
                         "completed": sorted(self._completed),
                         "live": sorted(self._live)},
            "vars": {
                n: np.array(self.scope.get(n))
                for n in self.scope.local_var_names()
            },
            "sparse": {
                k: {
                    kk: (np.array(vv) if isinstance(vv, np.ndarray) else vv)
                    for kk, vv in info.items()
                    if kk == "tbl"
                    or kk.startswith(("moment", "beta", "velocity"))
                }
                for k, info in self.sparse_tables.items()
            },
        }

    def _manifest_path(self, dir=None):
        import os

        return os.path.join(
            dir or self.checkpoint_dir,
            "pserver_%d.manifest.json" % self.server_idx,
        )

    def _write_snapshot(self, data, dir=None):
        """Atomic write-tmp + rename (the Go pserver's crc+rename
        discipline, service.go:346); runs OFF the service lock.  `dir`
        overrides the server's own checkpoint_dir for trainer-requested
        snapshots.  A crc-carrying manifest lands (atomically) AFTER the
        snapshot: restore verifies the crc, so silent corruption is
        detected; a crash between the two renames leaves a stale manifest
        over a complete snapshot, which restore recognizes and repairs
        (see load_checkpoint)."""
        import json
        import os
        import pickle
        import zlib

        target = dir or self.checkpoint_dir
        own_home = target == self.checkpoint_dir
        os.makedirs(target, exist_ok=True)
        path = self._ckpt_path(dir=target)
        tmp = path + ".tmp"
        with self._ckpt_write_lock:
            if own_home:
                # stale-writer guard: background writers can land out of
                # order, and an older round must never overwrite a newer
                # snapshot — its journal segments may already be gone
                rnd = int(data.get("round", 0))
                if rnd < self._ckpt_written_round:
                    return
                self._ckpt_written_round = rnd
            payload = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            manifest = {
                "round": int(data.get("round", 0)),
                "file": os.path.basename(path),
                "nbytes": len(payload),
                "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                "server_idx": self.server_idx,
                # async journal rotation point: restore replays journal
                # segments >= this (absent/None for sync snapshots) —
                # observability for operators and the chaos fences
                "journal_seg": data.get("journal_seg"),
            }
            mtmp = self._manifest_path(dir=target) + ".tmp"
            with open(mtmp, "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(mtmp, self._manifest_path(dir=target))
            # the snapshot is durable: journal segments it contains are
            # no longer needed for replay (crash BEFORE this point keeps
            # them, so the previous snapshot still has its full tail)
            jseg = data.get("journal_seg")
            if own_home and jseg is not None:
                for seg in self._journal_segments():
                    if seg < int(jseg):
                        try:
                            os.remove(self._journal_path(seg))
                        except OSError:
                            pass

    def save_checkpoint(self, dir=None):
        if not (dir or self.checkpoint_dir):
            return False
        self._write_snapshot(self._snapshot(), dir=dir)
        return True

    def load_checkpoint(self):
        """Restore shard state from the latest snapshot; returns the
        restored round, or None when no (usable) checkpoint exists.  A
        corrupt / truncated snapshot is reported and SKIPPED — a
        restarting pserver must come up (cold) rather than crash-loop on
        a bad file.  A crc MISMATCH alone is not fatal when the snapshot
        itself parses cleanly: a kill between the snapshot rename and the
        manifest rename leaves a STALE manifest next to a complete,
        atomically-renamed snapshot — that window must stay recoverable
        (the manifest is rewritten to match)."""
        if not self.checkpoint_dir:
            return None
        import json
        import os
        import pickle
        import sys
        import zlib

        path = self._ckpt_path()
        if not os.path.exists(path):
            # no snapshot ever landed: the journal (never rotated without
            # one) holds the ENTIRE applied-update history since birth —
            # replaying it from segment 0 is a full recovery
            if self._replay_journal(0):
                return self._round
            return None
        try:
            with open(path, "rb") as f:
                payload = f.read()
            mpath = self._manifest_path()
            crc_note = None
            if os.path.exists(mpath):
                try:
                    with open(mpath) as f:
                        manifest = json.load(f)
                    crc = zlib.crc32(payload) & 0xFFFFFFFF
                    if (len(payload) != int(manifest["nbytes"])
                            or crc != int(manifest["crc32"])):
                        crc_note = (
                            "manifest says %s bytes crc %08x, file is %d "
                            "bytes crc %08x" % (manifest["nbytes"],
                                                int(manifest["crc32"]),
                                                len(payload), crc))
                except (ValueError, KeyError, OSError) as e:
                    crc_note = "manifest unreadable: %s" % e
            else:
                crc_note = "no manifest (pre-manifest-era checkpoint)"
            data = pickle.loads(payload)
            if not (isinstance(data, dict) and "vars" in data):
                raise ValueError("snapshot has no vars table")
        except Exception as e:
            sys.stderr.write(
                "PSERVER checkpoint %s unusable, starting cold: %s\n"
                % (path, e))
            self._journal_quarantine()
            return None
        # legacy bare-array sparse entries (pre-slot-state checkpoints):
        # upgrade in the loaded data itself so the rewrite below lands a
        # MODERN snapshot + crc manifest on disk
        sparse = data.get("sparse", {})
        legacy = any(not isinstance(v, dict) for v in sparse.values())
        if legacy:
            data = dict(data)
            data["sparse"] = {
                k: (v if isinstance(v, dict)
                    else {"tbl": np.ascontiguousarray(v)})
                for k, v in sparse.items()}
        if crc_note is not None or legacy:
            # stale/missing manifest (crash landed between the two
            # renames, or a pre-manifest/legacy-format checkpoint) over a
            # snapshot that parses cleanly: recover, rewrite both files
            # in the modern format
            sys.stderr.write(
                "PSERVER checkpoint %s: %s; snapshot parsed cleanly — "
                "restoring and rewriting snapshot + manifest\n"
                % (path, crc_note or "legacy sparse format"))
            try:
                self._write_snapshot(data)
            except OSError:
                pass
        # live shard migration: re-adopt handed-off shards BEFORE the
        # vars/sparse restore (the sparse loop skips tables this server
        # doesn't know), and re-drop migrated-away shards the transpile-
        # time attrs would otherwise resurrect into double ownership
        mig = data.get("migration") or {}
        if mig.get("world"):
            self._ps_world = [str(e) for e in mig["world"]]
        self._sparse_shard_idx.update(
            {str(k): int(v)
             for k, v in (mig.get("shard_idx") or {}).items()})
        for g, pj in sorted((mig.get("programs") or {}).items()):
            if g not in self.grad_to_shard:
                self.grad_to_shard[g] = len(self.shard_programs)
                self.shard_programs.append(framework.Program.from_json(pj))
            self._adopted["programs"][g] = pj
        for shard, spec in sorted((mig.get("sparse") or {}).items()):
            if shard not in self.sparse_tables:
                self.sparse_tables[shard] = {
                    "tbl": np.zeros((0, 1), np.float32),  # data["sparse"]
                    "lr": spec.get("lr"),                 # fills it below
                    "opt": spec.get("opt") or {"type": "sgd",
                                               "attrs": {}}}
            if int(spec.get("s", -1)) >= 0:
                self._sparse_shard_idx[shard] = int(spec["s"])
            self._adopted["sparse"][shard] = dict(spec)
        if mig.get("lr_program") and self.lr_program is None:
            self.lr_program = framework.Program.from_json(
                mig["lr_program"])
            self._adopted["lr_program"] = mig["lr_program"]
        self._dropped_vars |= set(mig.get("dropped_vars") or [])
        for name in mig.get("dropped") or []:
            si = self.grad_to_shard.pop(name, None)
            if si is not None:
                self.shard_programs[si] = None
                self._fused = None
                self._fused_ready = False
            self.sparse_tables.pop(name, None)
            if name not in self._adopted["dropped"]:
                self._adopted["dropped"].append(name)
        self._recalc_lr_trigger_locked()
        for n, v in data["vars"].items():
            self.scope.set(n, v)
        for k, v in data["sparse"].items():
            if k not in self.sparse_tables:
                continue
            info = self.sparse_tables[k]
            for kk, vv in v.items():
                info[kk] = (np.ascontiguousarray(vv)
                            if isinstance(vv, np.ndarray) else vv)
        self._round = int(data.get("round", 0))
        folded = data.get("folded") or {}
        self._folded_send = {int(t): int(s)
                             for t, s in (folded.get("send") or {}).items()}
        self._folded_fetch = {int(t): int(s)
                              for t, s in (folded.get("fetch") or {}).items()}
        departed = data.get("departed") or {}
        self._evicted |= {int(t) for t in departed.get("evicted", [])}
        self._completed |= {int(t) for t in departed.get("completed", [])}
        # elastic ranks the dead incarnation had admitted (absent in
        # pre-elastic snapshots: range(num_trainers) stays the base)
        self._live |= {int(t) for t in departed.get("live", [])}
        plan = data.get("plan") or {}
        self._plan_epoch = max(self._plan_epoch,
                               int(plan.get("epoch", 0)))
        import time as _time

        # the open phase restarts at THIS incarnation's round/clock: the
        # dead incarnation already reported its rounds in its own stats,
        # and carrying round0=0 forward would double-count every
        # pre-restart round in the next closed phase (corrupting the
        # steps/s-per-membership evidence)
        self._phase.update(epoch=self._plan_epoch, round0=self._round,
                           t0=_time.monotonic())
        self._live -= (self._evicted | self._completed)
        self._phase["world"] = len(self._live)
        if not self._live:
            # everyone the snapshot knew is gone: nothing left to serve
            # (a rejoin would re-arm via register/_admit_locked)
            self._done.set()
        if self.sync_mode and self._round > 0:
            # the restored params ARE a completed round's output: serve
            # them.  Leaving params_ready False would park every
            # replaying get on a flag only the NEXT round sets — a
            # restart during the fetch phase would deadlock the job.
            self._params_ready = True
        # async delivery fences + clocks: restore from the snapshot, then
        # let journal replay advance them past it
        aseq = data.get("async_seq") or {}
        self._sparse_fence = {
            (int(t), str(tb)): int(s)
            for (t, tb), s in (aseq.get("sparse") or {}).items()}
        self._dense_fence = {
            int(t): [int(st[0]), set(int(x) for x in st[1])]
            for t, st in (aseq.get("dense") or {}).items()}
        self._trainer_clock = {
            int(t): int(c) for t, c in (aseq.get("clock") or {}).items()}
        jseg = data.get("journal_seg")
        if jseg is not None:
            # the snapshot coordinated with the journal: replay the
            # segments it does not contain — zero applied updates lost
            self._replay_journal(int(jseg))
        return self._round

    def _maybe_checkpoint(self):
        """Called under the service lock: snapshot cheaply here, serialize
        + write on a background thread so trainer RPCs never stall on disk."""
        if not (self.checkpoint_dir and self._round % self.checkpoint_every == 0):
            return
        try:
            data = self._snapshot()
        except Exception:
            import traceback

            traceback.print_exc()
            return

        def write():
            try:
                self._write_snapshot(data)
            except Exception:
                import traceback

                traceback.print_exc()

        threading.Thread(target=write, daemon=True).start()

    # ---- liveness / eviction --------------------------------------------
    def _touch(self, trainer_id):
        """Any verb from a tracked trainer counts as contact — a trainer
        mid-barrier is provably alive even if a heartbeat got delayed."""
        import time

        tid = int(trainer_id)
        if tid in self._tracked:
            self._tracked[tid] = time.monotonic()

    def _h_heartbeat(self, trainer_id=0):
        import time

        with self._cv:
            tid = int(trainer_id)
            live = tid in self._live
            if live:
                # first beat makes the trainer evictable from here on
                self._tracked[tid] = time.monotonic()
                self._ensure_reaper_locked()
            # an evicted trainer is NOT re-admitted: its grads were
            # dropped mid-round, re-joining would corrupt barrier math —
            # it learns it is dead from live=False and should exit
            return self._plan_reply_locked(
                {"ok": True, "live": live, "round": self._round})

    def _h_evict(self, trainer_id=0, respawn=False):
        """Out-of-band death report (the launcher's supervisor role): a
        trainer that died before its first heartbeat was never tracked,
        so the reaper can't see it — whoever reaped the process tells us.
        Unlike `complete`, this drops the ghost's pending grads / queued
        sparse rows and stale barrier entries (the full _evict_locked
        cleanup), so a partial round contribution never leaks.

        `respawn=True` (a supervised child: its replacement IS coming)
        parks the id as a pending join BEFORE the eviction, so the
        eviction's own boundary re-check readmits it — without this, the
        sole trainer's death would empty the live set and declare the
        job done while the supervisor is still booting the replacement,
        and the exiting pserver would strand that replacement forever."""
        with self._cv:
            tid = int(trainer_id)
            if respawn:
                # parked in BOTH modes: async has no barriers, so the
                # boundary check admits immediately — but without the
                # park an async sole-trainer death would still empty the
                # live set and exit the pserver under the replacement
                self._pending_joins.add(tid)
            else:
                # TERMINAL evict (restart budget exhausted, or a policy
                # retirement): the id is never coming back — unpark any
                # earlier respawn-optimistic report so the server does
                # not keep the job alive for a replacement that will
                # never boot
                self._pending_joins.discard(tid)
            self._evict_locked(tid, "reported dead")
            # _evict_locked early-returns for an id not in the live set
            # (already evicted / completed): a parked respawn join must
            # still admit if the server sits at a boundary
            self._admit_pending_joins_locked()
            if not respawn and not self._live and not self._pending_joins:
                # the terminal evict emptied the world: the job is over
                # NOW, not at the eviction deadline
                self._done.set()
                self._cv.notify_all()
            return {"ok": True, "live": len(self._live)}

    def _ensure_reaper_locked(self):
        # eviction is historically a SYNC-mode concept: async mode has no
        # barrier a ghost can hang, and evicting a merely-partitioned
        # async trainer would reject its (harmless) updates when it
        # heals.  With a staleness bound ARMED, async grows the same
        # liveness dependency — a dead laggard would park every fast peer
        # forever — so the reaper runs there too (eviction frees the
        # bound, preserving the PR 1 progress guarantee).
        if (self._reaper is not None or self._done.is_set()
                or not (self.sync_mode or self._staleness_bound > 0)):
            return
        t = threading.Thread(target=self._reaper_loop, daemon=True,
                             name="pserver-reaper-%d" % self.server_idx)
        self._reaper = t
        t.start()

    def _reaper_loop(self):
        """Evict tracked trainers that miss the deadline.  Polls at a
        fraction of the deadline so eviction lands within ~1.25x of it.
        One eviction's round re-evaluation failing must not kill the
        reaper — a dead reaper silently re-introduces the barrier
        deadlock this thread exists to break."""
        import time

        period = max(0.05, self.eviction_deadline / 4.0)
        while not self._done.wait(period):
            try:
                with self._cv:
                    now = time.monotonic()
                    dead = [
                        t for t, seen in self._tracked.items()
                        if t in self._live
                        and now - seen > self.eviction_deadline
                    ]
                    for t in dead:
                        self._evict_locked(
                            t, "missed liveness deadline (%.1fs)"
                            % self.eviction_deadline)
            except Exception:
                import traceback

                traceback.print_exc()

    def _clear_round_state_locked(self, tid):
        """Drop one trainer's partial contribution to the CURRENT round:
        unsummed dense grads, queued sparse rows, stale barrier entries
        and in-progress bucket-stream counts.  Shared by eviction (the
        ghost's state must not leak) and re-registration (a fresh trainer
        incarnation restarts its stream from scratch)."""
        for gname, per_trainer in self._pending.items():
            if per_trainer.pop(tid, None) is not None:
                # the ghost's grads were already folded into the running
                # partial: rebuild that grad's sum from the survivors
                # (in arrival order — same float result as a fresh fold)
                self._refold_partial_locked(gname)
        # prune grads left with NO contributors: an empty inner dict
        # would keep _mid_round_locked() True forever, so the round
        # boundary (and with it every parked rejoin) would never arrive
        self._pending = {g: per for g, per in self._pending.items() if per}
        self._partial = {g: t for g, t in self._partial.items()
                         if g in self._pending}
        self._pending_sparse = {
            k: v for k, v in self._pending_sparse.items() if k[0] != tid
        }
        self._send_barriers.discard(tid)
        self._fetch_barriers.discard(tid)
        self._send_bucket_counts.pop(tid, None)
        self._fetch_bucket_counts.pop(tid, None)
        self._send_step.pop(tid, None)
        self._send_seen.pop(tid, None)
        self._fetch_step.pop(tid, None)
        self._fetch_seen.pop(tid, None)

    def _refold_partial_locked(self, gname):
        """Recompute one grad's running partial from its per-trainer
        record (rare paths only: eviction, a fenced replay overwriting a
        slot).  Insertion order == arrival order, so the rebuilt sum is
        float-identical to an uninterrupted incremental fold."""
        total = None
        for v in self._pending.get(gname, {}).values():
            total = v if total is None else total + v
        if total is None:
            self._partial.pop(gname, None)
        else:
            self._partial[gname] = total

    def _fold_pending_locked(self, gname, tid, value):
        """Record one trainer's dense contribution AND fold it into the
        running partial sum at arrival time — the round-time per-trainer
        summation loop becomes a dict pop in _run_round."""
        per = self._pending.setdefault(gname, {})
        if tid in per:
            # fenced replay re-delivering a slot it already filled:
            # overwrite (never accumulate) and rebuild this partial
            per[tid] = value
            self._refold_partial_locked(gname)
            return
        per[tid] = value
        cur = self._partial.get(gname)
        self._partial[gname] = value if cur is None else cur + value

    def _reset_stream_locked(self, tid):
        """Full per-trainer stream reset: round state PLUS the fold
        fences.  For any transition that starts a FRESH incarnation
        lineage for the id (eviction, admission, re-registration) — a
        stale fold fence would drop the new process's first rounds as
        replays, since its step tokens restart at 1."""
        self._clear_round_state_locked(tid)
        self._folded_send.pop(tid, None)
        self._folded_fetch.pop(tid, None)

    def _evict_locked(self, trainer_id, why):
        """Remove a dead trainer from the round (called under self._cv):
        drop its unsummed dense grads and queued sparse rows, then
        re-evaluate pending barriers against the surviving live set — the
        round must complete instead of hanging on a ghost."""
        tid = int(trainer_id)
        if tid not in self._live:
            return
        self._live.discard(tid)
        self._tracked.pop(tid, None)
        # a departed trainer's clock must not hold the staleness bound:
        # dropping it (and the notify below) releases parked peers
        self._trainer_clock.pop(tid, None)
        self._evicted.add(tid)
        self.counters["evictions"] += 1
        print("PSERVER EVICT trainer=%d round=%d: %s"
              % (tid, self._round, why), flush=True)
        self._reset_stream_locked(tid)
        # durable membership shrink: a new plan epoch is due (minted at
        # the next boundary — or right here when no round is in flight)
        self._mark_plan_dirty_locked()
        # a joiner parked in `register` is ALIVE: an eviction that
        # exposed a round boundary admits it (and an empty live set must
        # admit rather than declare the job done)
        self._admit_pending_joins_locked()
        if not self._live:
            self._done.set()
        elif self.sync_mode:
            self._reeval_barriers_locked()
        self._cv.notify_all()

    # ---- elastic autoscaling: plan epochs -------------------------------
    def _mark_plan_dirty_locked(self):
        """The live set changed durably: a new plan epoch is due.  The
        mint itself is deferred to the next round boundary (sync mode) —
        bumping mid-assembly would stale-fence the survivors' own
        in-flight frames and hang the round they are completing."""
        self._plan_dirty = True
        self._maybe_mint_plan_locked()

    def _maybe_mint_plan_locked(self):
        """Mint the pending plan epoch if we are at a boundary (async
        mode has no rounds, so dirty mints immediately).  Closes the
        current membership phase for the phase log."""
        if not self._plan_dirty:
            return
        if self.sync_mode and not self._at_boundary_locked():
            return
        if not self._live:
            # an empty world has nobody to plan for: stay dirty — if a
            # parked join readmits, its admission re-triggers the mint
            # with a real world; if the job is truly over, the flag
            # dies with the server
            return
        import time

        now = time.monotonic()
        self._phases.append({
            "epoch": self._phase["epoch"], "world": self._phase["world"],
            "rounds": self._round - self._phase["round0"],
            "wall_s": round(now - self._phase["t0"], 3)})
        self._plan_epoch += 1
        self._plan_dirty = False
        self.counters["plan_epochs"] += 1
        self._phase = {"epoch": self._plan_epoch,
                       "world": len(self._live),
                       "round0": self._round, "t0": now}
        print("PSERVER PLAN-EPOCH epoch=%d world=%d round=%d"
              % (self._plan_epoch, len(self._live), self._round),
              flush=True)
        self._cv.notify_all()

    def _phases_snapshot_locked(self):
        """Closed phases plus the still-open one — the per-membership
        steps/s evidence PSERVER-STATS and the bench elastic leg read."""
        import time

        return self._phases + [{
            "epoch": self._phase["epoch"], "world": self._phase["world"],
            "rounds": self._round - self._phase["round0"],
            "wall_s": round(time.monotonic() - self._phase["t0"], 3)}]

    def _stale_plan_locked(self, pepoch):
        """True when a frame carries a plan epoch older than the
        server's — the sender has not yet re-derived its plan for the
        current world.  Fenced exactly like a stale incarnation: the
        frame is dropped (counted) and the reply tells the sender which
        epoch to re-plan for; folding it would mix grad scales from two
        different worlds into one round (or resurrect a dead round's
        stream after a membership change)."""
        if pepoch is None or int(pepoch) >= self._plan_epoch:
            return False
        self.counters["stale_plan_drops"] += 1
        return True

    def _h_plan(self, trainer_id=0):
        """The re-plan handshake: the current plan epoch and world size.
        Trainers call this when a reply reveals a newer epoch, then
        re-derive their plan (transpiler.derive_plan) for the returned
        world."""
        with self._cv:
            return {"epoch": self._plan_epoch,
                    "world": max(1, len(self._live)),
                    "live": sorted(self._live),
                    "trainers": self.num_trainers,
                    # live pserver migration: the CURRENT pserver world
                    # — trainers re-derive block/shard dispatch over it
                    # (empty for pre-migration servers: the client then
                    # keeps its spec endpoints)
                    "endpoints": list(self._ps_world)}

    def _plan_reply_locked(self, reply):
        """Stamp the current plan epoch into a reply ONCE elasticity has
        engaged (epoch > 0): trainers note it passively off their normal
        traffic and re-plan at their next step.  Epoch-0 replies stay
        byte-identical to the pre-elastic wire."""
        if self._plan_epoch > 0:
            reply["pepoch"] = self._plan_epoch
        return reply

    # ---- live pserver shard migration (journaled handoff) ----------------
    # docs/FAULT_TOLERANCE.md "Live shard migration".  Two-phase, driven
    # by the supervisor (or an admin `migrate` client):
    #   migrate_begin(world) — wait for a round boundary, FREEZE state
    #     mutation, serialize every shard this server owns under the OLD
    #     dispatch but not the NEW one as crc-framed journal records, and
    #     ship them to their new owners (`migrate_in`), which apply them
    #     through the same live paths journal replay uses and fsync a
    #     snapshot BEFORE acking.  Any failure aborts: unfreeze, keep
    #     everything, old assignment stays authoritative.
    #   migrate_commit(world) — adopt the new pserver world, drop the
    #     moved state, unfreeze, and mint the plan epoch.  The supervisor
    #     only commits after EVERY server's begin acked, so the epoch
    #     provably never mints before target durability.
    # A timed-out freeze self-aborts (a dead supervisor must throttle the
    # cluster, never deadlock it); the later commit then reads stale and
    # the supervisor restarts the whole handoff, re-capturing fresh state
    # (migrate_in overwrites by name — idempotent).
    def _recalc_lr_trigger_locked(self):
        """The async lr-program trigger is keyed to ONE designated grad
        (min name) — migration adding or removing shards must re-derive
        it, or a server whose trigger shard moved away stops advancing
        its lr schedule (and the rowless slot-state catch-up keyed to
        it), and an elastic-grown server would never start."""
        self._lr_trigger = (min(self.grad_to_shard)
                            if self.grad_to_shard else None)

    def _freeze_wait_locked(self):
        """Park a state-mutating verb while a shard handoff is capturing
        /shipping.  Bounded like the staleness park: freeze throttles,
        never deadlocks."""
        if not self._frozen:
            return
        limit = max(10.0, 3.0 * self.eviction_deadline)
        self._cv.wait_for(
            lambda: not self._frozen or self._done.is_set(),
            timeout=limit)

    def _mig_frame(self, rec):
        """One journal-format frame: [8B len][4B crc32][pickle] — the
        exact on-disk record framing, reused as the handoff transport so
        the receiver validates and replays with the same discipline."""
        import pickle
        import zlib

        payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        return _J_HEAD.pack(len(payload),
                            zlib.crc32(payload) & 0xFFFFFFFF) + payload

    @staticmethod
    def _mig_unframe(frame):
        """Validate + decode one handoff frame; raises on length/crc
        mismatch (a torn frame must fail the handoff loudly, exactly as
        a torn journal record ends a replay — never apply garbage)."""
        import pickle
        import zlib

        if len(frame) < _J_HEAD.size:
            raise ValueError("migration frame shorter than its header")
        ln, crc = _J_HEAD.unpack_from(frame, 0)
        payload = frame[_J_HEAD.size:]
        if ln != len(payload) or ln > _J_MAX_RECORD:
            raise ValueError("migration frame length mismatch")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise ValueError("migration frame crc mismatch")
        return pickle.loads(payload)

    def _derive_ps_plan(self, endpoints):
        from ..transpiler.distribute_transpiler import derive_plan

        return derive_plan(self.plan_spec,
                           world={"endpoints": list(endpoints)})

    def _serialize_dense_shard_locked(self, gblock, idx):
        """One moving dense shard as a journal record: its optimizer
        shard program plus every per-block persistable var (param block,
        sliced moments, private beta pows — everything suffixed with
        this block's index).  Whole (shared) vars ship separately."""
        prog = self.shard_programs[self.grad_to_shard[gblock]]
        suffix = ".block%d" % int(idx)
        vars_out, whole = {}, {}
        for name, v in sorted(prog.global_block().vars.items()):
            if not getattr(v, "persistable", False):
                continue
            cur = self.scope.find_var(name)
            if cur is None:
                continue
            if name.endswith(suffix):
                vars_out[name] = np.array(cur)
            else:
                whole[name] = np.array(cur)
        return ({"k": "mshard", "g": gblock, "i": int(idx),
                 "prog": prog.to_json(), "vars": vars_out}, whole)

    def _serialize_sparse_shard_locked(self, shard):
        info = self.sparse_tables[shard]
        payload = {
            kk: (np.array(vv) if isinstance(vv, np.ndarray) else vv)
            for kk, vv in info.items()
            if kk in ("tbl", "lr", "opt")
            or kk.startswith(("moment", "beta", "velocity"))}
        fences = {str(t): int(sq)
                  for (t, tb), sq in self._sparse_fence.items()
                  if tb == shard}
        return {"k": "mtable", "t": str(shard),
                "s": int(self._sparse_shard_idx.get(shard, -1)),
                "info": payload, "fences": fences}

    def _serialize_sparse_tail_locked(self, shard):
        """Frozen FINAL TAIL of a delta handoff: only the rows dirtied
        since the unfrozen snapshot shipped, plus the non-row scalars
        (adam beta pows, lr) and the fold fences — the target overlays
        them on the snapshot it already holds, reconstructing the exact
        frozen state.  Falls back to the full record when row tracking
        went whole-table (momentum decay, shard bounce-back)."""
        d = (self._mig_dirty or {}).get(shard, None)
        if self._mig_dirty is None or shard not in self._mig_dirty \
                or d is None:
            return self._serialize_sparse_shard_locked(shard)
        info = self.sparse_tables[shard]
        ids = np.asarray(sorted(d), np.int64)
        rows = {}
        for kk, vv in info.items():
            if isinstance(vv, np.ndarray) and (
                    kk == "tbl"
                    or kk.startswith(("moment", "velocity"))):
                rows[kk] = np.array(vv[ids]) if ids.size else \
                    np.zeros((0,) + vv.shape[1:], vv.dtype)
        scal = {kk: vv for kk, vv in info.items()
                if kk == "lr" or (kk.startswith("beta")
                                  and not isinstance(vv, np.ndarray))}
        fences = {str(t): int(sq)
                  for (t, tb), sq in self._sparse_fence.items()
                  if tb == shard}
        return {"k": "mrows", "t": str(shard), "i": ids, "rows": rows,
                "scal": scal, "fences": fences}

    def _moving_sets_locked(self, new_world):
        """The shards THIS server owns under the old dispatch but not
        the new: [(gblock, new_ep, idx), ...], [(shard, new_ep), ...].
        Shared by the begin capture and the restart-recovery commit."""
        old_plan = self._derive_ps_plan(self._ps_world)
        new_plan = self._derive_ps_plan(new_world)
        grads = {str(p): str(g) for p, _s, _d, g in
                 self.plan_spec["params"]}
        dense, sparse = [], []
        for (p, idx), old_ep in sorted(old_plan["block_eps"].items()):
            if old_ep != self.endpoint:
                continue
            new_ep = new_plan["block_eps"][(p, idx)]
            if new_ep == self.endpoint:
                continue
            gblock = "%s.block%d" % (grads[p], idx)
            if gblock not in self.grad_to_shard:
                continue  # already handed off (idempotent retry)
            dense.append((gblock, new_ep, int(idx)))
        for shard, s in sorted(self._sparse_shard_idx.items()):
            if shard not in self.sparse_tables:
                continue
            old_ep = old_plan["sparse_eps"][s]
            new_ep = new_plan["sparse_eps"][s]
            if old_ep != self.endpoint or new_ep == self.endpoint:
                continue
            sparse.append((shard, new_ep))
        return dense, sparse

    def _shard_var_names_locked(self, gblock, idx):
        """Persistable per-block vars of one dense shard (the state that
        moves with it)."""
        prog = self.shard_programs[self.grad_to_shard[gblock]]
        suffix = ".block%d" % int(idx)
        return sorted(
            n for n, v in prog.global_block().vars.items()
            if getattr(v, "persistable", False) and n.endswith(suffix))

    def _mig_capture_locked(self, new_world, delta=False):
        """Compute the moving set (old dispatch vs new) and serialize it
        into per-target frame lists.  Called frozen, at a boundary.
        `delta`: the sparse tables' full snapshots already shipped
        unfrozen — serialize only their dirty-row tails (dense shards,
        whole vars and fences always ship here, in the freeze)."""
        dense, sparse = self._moving_sets_locked(new_world)
        targets = {}   # ep -> [frame, ...]
        whole_all = {}
        moved_dense, moved_sparse = [], []
        for gblock, new_ep, idx in dense:
            rec, whole = self._serialize_dense_shard_locked(gblock, idx)
            targets.setdefault(new_ep, []).append(self._mig_frame(rec))
            whole_all.update(whole)
            moved_dense.append((gblock, new_ep, sorted(rec["vars"])))
        for shard, new_ep in sparse:
            rec = (self._serialize_sparse_tail_locked(shard) if delta
                   else self._serialize_sparse_shard_locked(shard))
            targets.setdefault(new_ep, []).append(self._mig_frame(rec))
            moved_sparse.append((shard, new_ep))
        if targets:
            # shared state a FRESH target needs: whole vars (scheduled
            # lr values, step counters) + the lr program; applied
            # set-if-absent so an established server's own copies win
            if self.lr_program is not None:
                for name, v in sorted(
                        self.lr_program.global_block().vars.items()):
                    if getattr(v, "persistable", False):
                        cur = self.scope.find_var(name)
                        if cur is not None:
                            whole_all.setdefault(name, np.array(cur))
            wrec = self._mig_frame({
                "k": "mwhole", "vars": whole_all,
                "lr_program": (self.lr_program.to_json()
                               if self.lr_program is not None else None)})
            # the per-trainer FOLD FENCES travel with the state: the
            # captured shards already contain every round this server
            # folded, and a post-flip re-ship of the transition round
            # must drop as dup_round at the NEW owner exactly as it
            # would have here — a fresh target without the fences would
            # apply an already-contained round a second time (the
            # double-apply race the 2->3 chaos E2E caught)
            frec = self._mig_frame({
                "k": "mfence",
                "send": {str(t): int(s)
                         for t, s in self._folded_send.items()},
                "fetch": {str(t): int(s)
                          for t, s in self._folded_fetch.items()}})
            for ep in targets:
                targets[ep].append(wrec)
                targets[ep].append(frec)
        return targets, moved_dense, moved_sparse

    def _abort_mig_locked(self, why):
        if self._mig is None and not self._frozen:
            return
        self.counters["migrate_aborts"] += 1
        print("PSERVER MIGRATE-ABORT ep=%s: %s"
              % (self.endpoint, why), flush=True)
        self._mig = None
        self._mig_dirty = None
        self._mig_gen += 1
        self._frozen = False
        self._cv.notify_all()

    def _mig_timeout(self, gen):
        with self._cv:
            if self._frozen and self._mig_gen == gen:
                self._abort_mig_locked(
                    "freeze timed out waiting for commit — the "
                    "supervisor died mid-handoff; unfreezing (the old "
                    "assignment stays authoritative)")

    def _h_migrate_begin(self, world, trainer_id=0, delta=False):
        """Phase 1 of the handoff (see section comment).

        ``delta=True`` — incremental delta handoff: the bulky sparse
        tables ship as an UNFROZEN snapshot first, while this server
        keeps serving and tracks which rows mutate (_mig_dirty); the
        freeze then covers only the FINAL TAIL — dirty rows (mrows),
        dense shards, whole vars, fences.  ``freeze_ms`` in the reply
        is that frozen window: with a large embedding shard it shrinks
        from ~the whole handoff to the dirty fraction, which is the
        point."""
        import time

        if not self.plan_spec or not self.endpoint:
            return {"ok": False,
                    "error": "no re-derivable plan spec: this server "
                             "cannot compute shard dispatch for a new "
                             "world (custom dispatcher or legacy "
                             "per-variable wire) — migration refused"}
        world = [str(e) for e in world]
        t0 = time.monotonic()
        limit = max(10.0, 3.0 * self.eviction_deadline)
        pre_bytes = 0
        if delta:
            # ---- phase 1a: unfrozen sparse snapshot + dirty tracking
            with self._cv:
                if self._frozen or self._mig is not None:
                    return {"ok": False, "busy": True}
                try:
                    _dense, snap_sparse = self._moving_sets_locked(world)
                    pre_targets = {}
                    for shard, new_ep in snap_sparse:
                        rec = self._serialize_sparse_shard_locked(shard)
                        pre_targets.setdefault(new_ep, []).append(
                            self._mig_frame(rec))
                except Exception as e:
                    import traceback

                    traceback.print_exc()
                    return {"ok": False,
                            "error": "delta snapshot failed: %s" % e}
                # arm dirty tracking BEFORE the lock drops: every row
                # an application touches from here on rides the tail
                self._mig_dirty = {shard: set()
                                   for shard, _ in snap_sparse}
            pre_bytes = sum(len(f) for frames in pre_targets.values()
                            for f in frames)
            snap_err = None
            from .rpc import RPCClient

            for ep, frames in sorted(pre_targets.items()):
                try:
                    r = RPCClient.get(ep).call(
                        "migrate_in", timeout_s=600.0, frames=frames,
                        source=self.endpoint)
                    if not (isinstance(r, dict) and r.get("ok")):
                        snap_err = ("target %s refused the snapshot: %r"
                                    % (ep, r))
                        break
                except Exception as e:
                    snap_err = ("target %s failed mid-snapshot: %s"
                                % (ep, e))
                    break
            if snap_err is not None:
                with self._cv:
                    self._mig_dirty = None
                return {"ok": False, "error": snap_err}
        with self._cv:
            if self._frozen or self._mig is not None:
                self._mig_dirty = None
                return {"ok": False, "busy": True}
            if not self._cv.wait_for(
                    lambda: self._at_boundary_locked()
                    or self._done.is_set(), timeout=limit):
                self._mig_dirty = None
                return {"ok": False, "busy": True,
                        "error": "no round boundary within %.0fs" % limit}
            self._frozen = True
            f0 = time.monotonic()  # the freeze window starts HERE
            self._mig_gen += 1
            gen = self._mig_gen
            try:
                targets, moved_dense, moved_sparse = \
                    self._mig_capture_locked(world, delta=delta)
            except Exception as e:
                import traceback

                traceback.print_exc()
                self._abort_mig_locked("capture failed: %s" % e)
                return {"ok": False, "error": "capture failed: %s" % e}
            nbytes = pre_bytes + sum(len(f)
                                     for frames in targets.values()
                                     for f in frames)
            self._mig = {"world": world, "gen": gen,
                         "dense": moved_dense, "sparse": moved_sparse,
                         "bytes": nbytes}
            timer = threading.Timer(limit, self._mig_timeout, args=(gen,))
            timer.daemon = True
            timer.start()
        if targets:
            # chaos hook: SIGKILL the SOURCE mid-serialize (captured,
            # nothing shipped) — the old assignment must stay
            # authoritative and the retried handoff re-captures fresh
            self._maybe_migrate_crash("serialize")
        # ship OUTSIDE the lock: the freeze keeps captured state
        # consistent while frames are on the wire, and reads/heartbeats
        # keep flowing.  Any target failure aborts the whole handoff —
        # the epoch never mints for a partial transfer.
        shipped = {}
        err = None
        from .rpc import RPCClient

        for ep, frames in sorted(targets.items()):
            try:
                r = RPCClient.get(ep).call(
                    "migrate_in", timeout_s=600.0, frames=frames,
                    source=self.endpoint)
                if not (isinstance(r, dict) and r.get("ok")):
                    err = "target %s refused the handoff: %r" % (ep, r)
                    break
                shipped[ep] = int(r.get("applied", 0))
            except Exception as e:
                err = "target %s failed mid-handoff: %s" % (ep, e)
                break
        with self._cv:
            if err is not None:
                self._abort_mig_locked(err)
                return {"ok": False, "error": err}
            if self._mig is None or self._mig.get("gen") != gen:
                # the freeze self-aborted while we were shipping
                return {"ok": False, "stale": True,
                        "error": "freeze timed out during shipping"}
            moved = len(moved_dense) + len(moved_sparse)
            self.counters["migrated_shards_out"] += moved
            self.counters["migrated_bytes_out"] += nbytes
        freeze_ms = (time.monotonic() - f0) * 1e3
        print("PSERVER MIGRATE-BEGIN ep=%s world=%s moved=%d bytes=%d "
              "ms=%.1f freeze_ms=%.1f delta=%d"
              % (self.endpoint, world, moved, nbytes,
                 (time.monotonic() - t0) * 1e3, freeze_ms, int(delta)),
              flush=True)
        return {"ok": True, "moved": moved, "bytes": nbytes,
                "targets": shipped,
                "ms": round((time.monotonic() - t0) * 1e3, 3),
                "freeze_ms": round(freeze_ms, 3)}

    def _h_migrate_commit(self, world, trainer_id=0):
        """Phase 2: adopt the new pserver world, drop moved state, mint.
        Only called by the driver after EVERY live server's begin acked
        (i.e. every moving shard is durable at its target)."""
        world = [str(e) for e in world]
        with self._cv:
            if self._mig is not None and self._mig["world"] != world:
                return {"ok": False, "stale": True}
            if self._mig is None:
                # RESTART-RECOVERY commit: this server was killed (and
                # restored) between its begin-ack and here — the capture
                # died with the old incarnation, but the driver only
                # commits after EVERY begin acked, so every moving shard
                # is already durable at its target.  Recompute the diff
                # and adopt; dropping our (possibly one-restart-round
                # stale) copies is the correct direction — the target's
                # shipped copy is the newer one.  Without this, the
                # driver would have to abort-and-re-begin AFTER another
                # server already minted, and the re-shipped stale copy
                # would overwrite rounds trainers applied at the target
                # in between (a lost update).
                if not self.plan_spec or not self.endpoint:
                    return {"ok": False, "stale": True}
                if world == self._ps_world:
                    # already committed before the kill: idempotent ack
                    return {"ok": True, "epoch": self._plan_epoch,
                            "retiring": self.endpoint not in world}
                limit = max(10.0, 3.0 * self.eviction_deadline)
                self._cv.wait_for(
                    lambda: self._at_boundary_locked()
                    or self._done.is_set(), timeout=limit)
                try:
                    dense, sparse = self._moving_sets_locked(world)
                except Exception as e:
                    return {"ok": False, "stale": True,
                            "error": "recovery diff failed: %s" % e}
                self._mig = {
                    "world": world, "gen": self._mig_gen,
                    "dense": [(g, ep,
                               self._shard_var_names_locked(g, idx))
                              for g, ep, idx in dense],
                    "sparse": sparse}
                print("PSERVER MIGRATE-COMMIT-RECOVERY ep=%s world=%s"
                      % (self.endpoint, world), flush=True)
            for gblock, _ep, var_names in self._mig["dense"]:
                si = self.grad_to_shard.pop(gblock, None)
                if si is not None:
                    self.shard_programs[si] = None
                for n in var_names:
                    self.scope.erase(n)
                    # a fetch of a dropped var under the old layout must
                    # answer stale_plan (re-plan + re-pull), never a
                    # KeyError crash
                    self._dropped_vars.add(n)
                self._adopted["programs"].pop(gblock, None)
                self._adopted["dropped"].append(gblock)
            for shard, _ep in self._mig["sparse"]:
                self.sparse_tables.pop(shard, None)
                for key in [k for k in self._sparse_fence
                            if k[1] == shard]:
                    del self._sparse_fence[key]
                self._adopted["sparse"].pop(shard, None)
                self._adopted["dropped"].append(shard)
            moved = len(self._mig["dense"]) + len(self._mig["sparse"])
            self._fused = None
            self._fused_ready = False
            self._recalc_lr_trigger_locked()
            self._ps_world = world
            retiring = (self.endpoint is not None
                        and self.endpoint not in world)
            self._mig = None
            self._mig_dirty = None
            self._mig_gen += 1  # disarms the freeze-timeout timer
            self._frozen = False
            if moved:
                self.counters["migrations_out"] += 1
            # the pserver membership changed durably: mint NOW (the
            # freeze held the server at a round boundary) so the next
            # trainer frame learns the new world
            self._mark_plan_dirty_locked()
            data = self._snapshot() if self.checkpoint_dir else None
            epoch = self._plan_epoch
            self._cv.notify_all()
        if data is not None:
            # synchronous: the new world (and the dropped shards) are
            # durable before the commit acks — a restart cannot
            # resurrect moved-away shards into double ownership
            self._write_snapshot(data)
        print("PSERVER MIGRATE-COMMIT ep=%s world=%s epoch=%d%s"
              % (self.endpoint, world, epoch,
                 " RETIRING" if retiring else ""), flush=True)
        return {"ok": True, "epoch": epoch, "retiring": retiring}

    def _h_migrate_abort(self, trainer_id=0):
        with self._cv:
            self._abort_mig_locked("driver requested abort")
            return {"ok": True}

    def _maybe_migrate_crash(self, point):
        """Deterministic chaos hook: PADDLE_TPU_MIGRATE_CRASH names the
        kill point ('recv' = before any record applies, 'ack' = after
        apply + fsync, before the ack leaves); the marker file (crash
        once) lets a supervised respawn run clean."""
        import os
        import signal

        if os.environ.get("PADDLE_TPU_MIGRATE_CRASH") != point:
            return
        marker = os.environ.get("PADDLE_TPU_MIGRATE_CRASH_ONCE")
        if marker and os.path.exists(marker):
            return
        if marker:
            with open(marker, "w") as f:
                f.write(point)
        print("PSERVER MIGRATE-CRASH point=%s" % point, flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    def _h_migrate_in(self, frames, source=None, trainer_id=0):
        """Target side of the handoff: validate each crc-framed journal
        record, apply it through the SAME paths journal replay uses,
        append it to this server's own journal (async mode), and fsync a
        snapshot BEFORE acking — acked == durable, so the source's
        commit (and the epoch mint behind it) can rely on it."""
        self._maybe_migrate_crash("recv")
        with self._cv:
            n = 0
            for frame in frames:
                rec = self._mig_unframe(frame)
                self._apply_journal_record(rec)
                self._journal_append_locked(rec)
                n += 1
                self.counters["migrated_bytes_in"] += len(frame)
                if rec.get("k") in ("mshard", "mtable"):
                    self.counters["migrated_shards_in"] += 1
            if n:
                self.counters["migrations_in"] += 1
            data = self._snapshot() if self.checkpoint_dir else None
        if data is not None:
            self._write_snapshot(data)  # fsync'd BEFORE the ack
        self._maybe_migrate_crash("ack")
        print("PSERVER MIGRATE-IN ep=%s source=%s records=%d durable=%s"
              % (self.endpoint, source, n, bool(self.checkpoint_dir)),
              flush=True)
        return {"ok": True, "applied": n,
                "durable": bool(self.checkpoint_dir)}

    def _h_retire(self, trainer_id=0):
        """Clean shutdown of a drained, migrated-away server: after its
        commit (all shards handed off, epoch minted, trainers
        re-planned), the driver retires it — the serve loop concludes
        and PSERVER-STATS prints, instead of an opaque SIGKILL."""
        with self._cv:
            print("PSERVER RETIRE ep=%s round=%d"
                  % (self.endpoint, self._round), flush=True)
            self._done.set()
            self._cv.notify_all()
            return {"ok": True}

    # ---- elastic rejoin --------------------------------------------------
    def _admit_locked(self, tid):
        """Admit a (re)joining trainer into the live set.  ONLY called at
        a round boundary: the barrier denominator must never grow while a
        round is being assembled, or survivors would wait on a joiner
        that was never part of the round."""
        was_evicted = tid in self._evicted
        grew = tid not in self._live
        self._live.add(tid)
        self._evicted.discard(tid)
        self._completed.discard(tid)
        self._reset_stream_locked(tid)
        self._done.clear()
        if was_evicted:
            self.counters["readmissions"] += 1
            print("PSERVER READMIT trainer=%d round=%d" % (tid, self._round),
                  flush=True)
        if grew:
            # admission only happens at a boundary, so the epoch mints
            # immediately: the joiner's very first `plan` fetch (and the
            # survivors' next-round re-plan) see the grown world
            self._mark_plan_dirty_locked()

    def _admit_pending_joins_locked(self):
        """Admit parked joins IF the server is at a round boundary —
        self-guarded, so it is safe (and necessary) to call from every
        state transition that can CREATE a boundary: _run_round, the
        fetch-barrier clears, eviction and completion."""
        if not self._pending_joins or not self._at_boundary_locked():
            return
        for tid in sorted(self._pending_joins):
            self._admit_locked(tid)
        self._pending_joins.clear()
        self._cv.notify_all()

    def _mid_round_locked(self):
        """True while the current round is being ASSEMBLED (some trainer
        has contributed grads or entered a barrier): admission now would
        change the barrier denominator under the survivors."""
        return bool(
            self._send_barriers or any(self._pending.values())
            or self._pending_sparse or self._send_seen
            or any(self._send_bucket_counts.values()))

    def _at_boundary_locked(self):
        """The round boundary: no round being assembled AND no fetch of
        the previously-served round still draining.  Admission while
        _fetch_barriers pends would grow the fetch denominator under the
        survivors — the stale entries could later complete with the
        joiner's first fetch and flip params_ready off while survivors
        still hold un-served gets (the _h_complete hazard, but
        re-introduced by growth instead of shrinkage)."""
        return not (self._mid_round_locked() or self._fetch_barriers
                    or self._fetch_seen
                    or any(self._fetch_bucket_counts.values()))

    def _h_register(self, trainer_id=0):
        """Trainer handshake + elastic (re)join.  A fresh trainer process
        declares itself: its per-step fold fences reset (its stream
        restarts at step 1), and if the id was evicted or completed it is
        readmitted — at a ROUND BOUNDARY only, blocking until the
        in-flight round completes so barrier totals stay consistent for
        both the joiner and the survivors (a fence, not a delay)."""
        import time

        with self._cv:
            tid = int(trainer_id)
            self.counters["registrations"] += 1
            if tid in self._live:
                # fast relaunch reusing a live id (died and came back
                # before eviction noticed): drop the old incarnation's
                # partial round state and stale fold fences
                self._reset_stream_locked(tid)
            elif not self.sync_mode or self._at_boundary_locked():
                self._admit_locked(tid)
            else:
                self._pending_joins.add(tid)
                self._cv.wait_for(
                    lambda: tid in self._live or self._done.is_set())
                self._pending_joins.discard(tid)
                if tid not in self._live:
                    return {"ok": False, "done": True,
                            "round": self._round}
            if tid in self._tracked:
                self._tracked[tid] = time.monotonic()
            self._cv.notify_all()
            return self._plan_reply_locked(
                {"ok": True, "live": True, "round": self._round,
                 "world": max(1, len(self._live)),
                 "incarnation": self.incarnation})

    def _h_stats(self, trainer_id=0):
        """Recovery observability: incarnation, round, live/evicted sets,
        the eviction/readmission counters, and — async mode — the
        per-trainer logical clocks, staleness bound, async send count and
        journal/park evidence (rpc.get_comm_stats's server-side
        sibling)."""
        with self._cv:
            # load-aware scaling signals (docs/FAULT_TOLERANCE.md "Live
            # shard migration"): server-side pending work the
            # supervisor's _ScalingPolicy polls live — queue depth is
            # the number of un-applied per-trainer contributions +
            # queued sparse chunks, pending_bytes their payload (the
            # server-side bytes-in-flight)
            qd = (sum(len(per) for per in self._pending.values())
                  + len(self._pending_sparse))
            pb = (sum(int(v.nbytes) for per in self._pending.values()
                      for v in per.values())
                  + sum(int(np.asarray(c[1]).nbytes)
                        for c in self._pending_sparse.values()))
            out = {"round": self._round, "incarnation": self.incarnation,
                   "live": sorted(self._live),
                   "evicted": sorted(self._evicted),
                   "async_sends": self._async_sends,
                   "staleness_bound": self._staleness_bound,
                   # elastic autoscaling evidence: the current epoch +
                   # the per-membership-phase round log
                   "plan_epoch": self._plan_epoch,
                   "world": len(self._live),
                   "phases": self._phases_snapshot_locked(),
                   "queue_depth": qd,
                   "pending_bytes": pb,
                   "ps_world": list(self._ps_world),
                   # runtime surface for the reduced legacy guarantee
                   # (journaled-but-unfenced per-var async path)
                   "unfenced_async": bool(self._unfenced_async),
                   # rpc dict keys must be strings (closed wire types)
                   "clocks": {str(t): c
                              for t, c in sorted(
                                  self._trainer_clock.items())}}
            out.update(self.counters)
            return out

    def _complete_fetch_barrier_locked(self):
        """Every live trainer folded its fetch: reset the serve epoch.
        The single home for the clear/flip/admit sequence — the fenced
        fold, the legacy fold, the explicit barrier verb and eviction
        re-evaluation all converge here."""
        self._fetch_barriers.clear()
        self._params_ready = False
        # fetch drained: a round boundary — parked joins admit, pending
        # plan epochs mint
        self._admit_pending_joins_locked()
        self._maybe_mint_plan_locked()
        self._cv.notify_all()

    def _reeval_barriers_locked(self):
        """The live set shrank (eviction / clean departure): pending
        barriers re-evaluate against the survivors.  FETCH first — a
        pending fetch barrier belongs to the round already SERVED, and
        re-evaluating it after _run_round would flip the fresh round's
        params_ready back off, hanging every surviving get on a flag
        nothing will set again."""
        if (self._fetch_barriers
                and len(self._fetch_barriers) >= len(self._live)):
            self._complete_fetch_barrier_locked()
        if (self._send_barriers
                and len(self._send_barriers) >= len(self._live)):
            self._run_round()
        else:
            # the shrink itself may have exposed a round boundary
            self._admit_pending_joins_locked()

    # ---- verb dispatch ---------------------------------------------------
    def handle(self, verb, **kw):
        tid = kw.get("trainer_id")
        if isinstance(tid, int) and tid in self._tracked:
            # lock-free liveness stamp at RECEIVE time (dict assignment
            # is GIL-atomic): a handler queued behind the round lock
            # while _run_round executes a long optimize step must not go
            # stale waiting — the reaper would mass-evict healthy
            # trainers the instant the round releases the lock
            import time

            self._tracked[int(tid)] = time.monotonic()
        try:
            return getattr(self, "_h_" + verb)(**kw)
        except Exception as e:  # ship errors to the client
            import traceback

            return {"__error__": "%s\n%s" % (e, traceback.format_exc())}

    # ---- optimize --------------------------------------------------------
    def _apply_shard(self, shard_idx, feed):
        prog = self.shard_programs[shard_idx]
        self.exe.run(prog, feed=feed, fetch_list=[], scope=self.scope)

    def _ensure_fused_locked(self):
        """Build the fused-apply plan on the first round (lazy: stub
        shard programs in unit tests must not crash the constructor).
        Any analysis surprise degrades to the per-block path, loudly."""
        if self._fused_ready:
            return self._fused
        self._fused_ready = True
        from ..flags import get_flag

        if not get_flag("ps_fused_apply"):
            return None
        try:
            from .fused_apply import FusedApply

            fused = FusedApply(self.shard_programs, self.grad_to_shard,
                               self.scope)
            if fused.specs:
                self._fused = fused
        except Exception:
            import traceback

            traceback.print_exc()
        return self._fused

    def _run_round(self):
        """All send-barriers in: run lr, apply the (arrival-time-folded)
        grad sums — one jitted fused call per optimizer group, per-block
        executor programs for anything unfusable — then the queued
        sparse updates (after lr, so a scheduled lr is this round's
        decayed value — the order the local program runs in)."""
        from ..profiler import RecordEvent

        if self.lr_program is not None:
            self.exe.run(self.lr_program, feed={}, fetch_list=[], scope=self.scope)
        totals = {}
        for gname, per_trainer in sorted(self._pending.items()):
            total = self._partial.get(gname)
            if total is None:  # defensive: fold record missing
                for v in per_trainer.values():
                    total = v if total is None else total + v
            totals[gname] = total
        fused = self._ensure_fused_locked()
        with RecordEvent("ps_apply_round", cat="apply"):
            if fused is not None:
                totals = fused.apply(totals)
            for gname in sorted(totals):
                self._apply_shard(self.grad_to_shard[gname],
                                  {gname: totals[gname]})
        self._partial.clear()
        by_table = {}
        for (tid, t) in sorted(self._pending_sparse):
            by_table.setdefault(t, []).append(self._pending_sparse[(tid, t)])
        for t, chunks in sorted(by_table.items()):
            self._apply_sparse(
                t,
                np.concatenate([c[0] for c in chunks]),
                np.concatenate([c[1] for c in chunks], axis=0),
                advance_pows=False,
            )
        self._pending_sparse = {}
        # per-round state that advances even on ROWLESS rounds: the
        # local op runs every step regardless of which rows a shard's id
        # hashing happened to receive — adam beta pows advance
        # (ops/optimizer_ops.py Beta1PowOut) and momentum velocity
        # decays (the densified SparseMomentumFunctor covers every row)
        for t, info in sorted(self.sparse_tables.items()):
            self._advance_pows(info)
            if t not in by_table and (
                    (info.get("opt") or {}).get("type") == "momentum"):
                self._apply_sparse(t, np.zeros((0,), np.int64),
                                   np.zeros((0, info["tbl"].shape[1]),
                                            info["tbl"].dtype),
                                   advance_pows=False)
        self._pending.clear()
        self._send_barriers.clear()
        # fetch-barrier stragglers from the PREVIOUS serve epoch (a
        # fenced replay's re-fold of a round its peers already finished
        # fetching — no survivor will ever complete that barrier) must
        # not carry into the new round: a leftover entry would let the
        # next round's fetch barrier complete one fold early, flipping
        # params_ready off under a trainer's still-unserved get
        self._fetch_barriers.clear()
        self._params_ready = True
        self._round += 1
        self._maybe_checkpoint()
        # round boundary: admit trainers parked in `register` — the NEXT
        # round's barrier totals include them from its very first bucket
        self._admit_pending_joins_locked()
        # ... and mint any pending plan epoch: a membership change that
        # landed mid-round becomes visible to trainers exactly one round
        # after it happened (their blocking send replies carry it)
        self._maybe_mint_plan_locked()
        self._cv.notify_all()

    # ---- handlers --------------------------------------------------------
    def _apply_async_send_locked(self, name, value):
        """One async dense grad application, lr-trigger bookkeeping
        included — the shared core of the live verbs AND journal replay,
        so a replayed stream advances the lr schedule and the sparse
        slot-state catch-up identically to the original arrivals."""
        if name == self._lr_trigger:
            if self.lr_program is not None:
                self.exe.run(
                    self.lr_program, feed={}, fetch_list=[],
                    scope=self.scope
                )
            # per-step catch-up for sparse tables that saw NO rows
            # since the last trigger: their adam beta-pows advance
            # and momentum velocity decays exactly as a sync
            # rowless round would (ADVICE r5; module docstring
            # documents the residual approximation)
            for t, info in sorted(self.sparse_tables.items()):
                if t in self._async_touched:
                    continue
                typ = (info.get("opt") or {}).get("type")
                if typ == "adam":
                    self._advance_pows(info)
                elif typ == "momentum":
                    self._apply_sparse(
                        t, np.zeros((0,), np.int64),
                        np.zeros((0, info["tbl"].shape[1]),
                                 info["tbl"].dtype),
                        advance_pows=False)
            self._async_touched.clear()
        self._apply_shard(self.grad_to_shard[name], {name: value})
        self._async_sends += 1

    def _async_dense_ckpt_locked(self):
        """Checkpoint cadence for async dense traffic, checked ONLY
        after the triggering bucket's journal record + fence commit are
        down.  Firing mid-bucket (the old per-send modulo inside the
        apply) captured a snapshot containing the bucket's effects and
        rotated the journal BEFORE that bucket's record was appended —
        the record then sat past the rotation point and a restore
        replayed it onto state that already contained it (double
        apply)."""
        if self._replaying or not self.checkpoint_dir:
            return
        cadence = self.checkpoint_every * max(1, len(self.grad_to_shard))
        if self._async_sends - self._sends_at_ckpt >= cadence:
            self._sends_at_ckpt = self._async_sends
            self._round += 1
            self._maybe_checkpoint()

    def _stale_shard_locked(self, names):
        """True when a frame names a grad shard this server no longer
        (or does not yet) own — the sender's dispatch predates a
        committed migration.  Replied like a stale plan: dropped, the
        sender re-plans and re-ships to the current owner."""
        if self.plan_spec is None:
            return False
        if any(n not in self.grad_to_shard for n in names):
            self.counters["stale_plan_drops"] += 1
            return True
        return False

    def _h_send(self, name, value, trainer_id=0):
        value = np.asarray(value)
        if not self.sync_mode:
            with self._cv:
                self._touch(trainer_id)
                self._freeze_wait_locked()
                if self._stale_shard_locked([name]):
                    return self._plan_reply_locked(
                        {"ok": True, "stale_plan": True,
                         "pepoch": self._plan_epoch})
                self._apply_async_send_locked(name, value)
                # legacy per-var path: journaled (a restart replays it)
                # but UNFENCED — only the bucketed path carries aseq
                # tokens, so exactly-once across SIGKILL needs buckets.
                # Surface the reduced guarantee at RUNTIME, loudly, the
                # first time it actually runs journaled (it used to be
                # documented only)
                if self._journal_enabled() and not self._unfenced_async:
                    self._unfenced_async = True
                    import sys

                    sys.stderr.write(
                        "PSERVER WARNING: legacy per-variable async "
                        "path (comm_bucket_bytes=0) is running "
                        "JOURNALED BUT UNFENCED — applied updates "
                        "survive SIGKILL, but an RPC retry straddling "
                        "a restart can double-apply (no aseq dedup).  "
                        "Use the bucketed wire "
                        "(FLAGS_comm_bucket_bytes>0) for exactly-once "
                        "(docs/FAULT_TOLERANCE.md)\n")
                self._journal_append_locked(
                    {"k": "v", "n": name, "v": value,
                     "tid": int(trainer_id)})
                self._async_dense_ckpt_locked()
            return {"ok": True}
        with self._lock:
            self._touch(trainer_id)
            if int(trainer_id) in self._evicted:
                # a ghost's late grads must not leak into a future round
                return {"ok": False, "evicted": True}
            self._fold_pending_locked(name, int(trainer_id), value)
        return {"ok": True}

    def _h_send_bucket(self, blocks, trainer_id=0, seq_total=None,
                       step=None, seq_idx=None, sparse_tables=None,
                       aseq=None, pepoch=None):
        """Coalesced grad frame: `blocks` maps grad block name -> value,
        shipped as ONE rpc round trip (see ops/dist_ops.py send_bucket).
        Server-side the bucket is unpacked into exactly the per-block
        paths _h_send uses — pending tables in sync mode, immediate shard
        application (with the lr-trigger bookkeeping) in async — so
        optimizer slot logic never sees the difference.

        `seq_total` (sync mode) folds the send barrier into the bucket
        stream: the trainer declares how many buckets it ships to THIS
        server per step, and the arrival of the last one (arrival ORDER
        is free — the window delivers out of order) counts as the
        trainer's send barrier, saving a dedicated blocking round trip.
        That last call blocks until the round runs, exactly like the
        explicit barrier verb it replaces.

        `step`/`seq_idx` (incarnation fencing) make the stream
        replay-safe: buckets are counted by (step, seq_idx) SET, so a
        trainer that re-ships its whole round after observing a pserver
        restart cannot advance the fold twice (pending slots are keyed —
        overwrite, not accumulate), and a replay of a step this server
        already FOLDED (it survived in the restored snapshot) is dropped
        at the `_folded_send` fence instead of double-applying a round."""
        if not self.sync_mode:
            # sorted order keeps the lr trigger (min grad name) firing
            # before the other shards of the same logical step WITHIN a
            # bucket.  Across buckets, comm_inflight > 1 can reorder
            # arrivals, so a multi-bucket async step may interleave the
            # trigger with another bucket's grads — one more term of the
            # documented async approximation (module docstring); sync
            # mode is exact, its ordering comes from the round barrier.
            with self._cv:
                self._touch(trainer_id)
                self._freeze_wait_locked()
                tid = int(trainer_id)
                if tid in self._evicted:
                    return {"ok": False, "evicted": True}
                if self._stale_shard_locked(blocks):
                    # migrated-away shard under a pre-flip dispatch: the
                    # async sender must re-plan and re-ship to the new
                    # owner (dropped here, never applied — and never
                    # journaled, so replay can't resurrect it either).
                    # dropped_aseq echoes the victim so the trainer's
                    # dense resend queue re-ships EXACTLY the dropped
                    # buckets (an applied-but-unacked one must not be
                    # re-shipped under a fresh aseq — that would bypass
                    # the dedup fence and double-apply)
                    return self._plan_reply_locked(
                        {"ok": True, "stale_plan": True,
                         "dropped_aseq": aseq,
                         "pepoch": self._plan_epoch})
                if aseq is not None and self._dense_fence_is_dup(tid, aseq):
                    # at-least-once re-delivery (RPC retry straddling a
                    # restart, or an incarnation-bump re-ship) of a bucket
                    # whose apply is already durable: drop, never double
                    self.counters["dedup_drops"] += 1
                    # dense_acked names the DENSE fence explicitly: the
                    # trainer drains this reply from a pipelined window
                    # mixed with other verbs' acks, and its dense resend
                    # queue must only prune on dense high-water
                    return self._plan_reply_locked(
                        {"ok": True, "dup": True,
                         "acked": self._dense_fence[tid][0],
                         "dense_acked": self._dense_fence[tid][0]})
                # NOTE: aseq never feeds _trainer_clock — it counts
                # BUCKETS per endpoint, not steps, so a multi-bucket
                # model would inflate a laggard's clock by the bucket
                # count and silently defeat the staleness bound.  The
                # clock is the sparse seq token alone (minted once per
                # STEP and shipped to every server, empties included).
                vals = {n: np.asarray(v) for n, v in blocks.items()}
                for name in sorted(vals):
                    self._apply_async_send_locked(name, vals[name])
                if aseq is not None:
                    # journal + fsync BEFORE the reply: an acked bucket is
                    # durable, an unacked one is re-shipped — exactly-once
                    # either way (the fence drops the dup)
                    self._journal_append_locked(
                        {"k": "d", "b": vals, "tid": tid, "q": aseq})
                    self._dense_fence_commit(tid, aseq)
                    self._async_dense_ckpt_locked()
                    return self._plan_reply_locked(
                        {"ok": True, "acked": self._dense_fence[tid][0],
                         "dense_acked": self._dense_fence[tid][0]})
                self._journal_append_locked(
                    {"k": "d", "b": vals, "tid": tid, "q": None})
                self._async_dense_ckpt_locked()
                return self._plan_reply_locked({"ok": True})
            return {"ok": True}
        with self._cv:
            self._touch(trainer_id)
            self._freeze_wait_locked()
            tid = int(trainer_id)
            if tid in self._evicted:
                return {"ok": False, "evicted": True}
            if self._stale_plan_locked(pepoch) \
                    or self._stale_shard_locked(blocks):
                # plan-epoch fence (elastic autoscaling): the sender's
                # world is out of date — its grads carry the OLD scale,
                # or name shards a committed migration moved away.
                # Dropped, never folded; the sender re-plans off the
                # reply and re-ships the round at the current epoch.
                return {"ok": True, "stale_plan": True,
                        "pepoch": self._plan_epoch}
            if seq_total and step is not None:
                step = int(step)
                if step <= self._folded_send.get(tid, -1):
                    # fenced replay of a round the restored state already
                    # contains: the fold record rode the same snapshot as
                    # the params, so applying again would double the round
                    self.counters["dup_round_drops"] += 1
                    return self._plan_reply_locked(
                        {"ok": True, "dup_round": True})
                prev = self._folded_send.get(tid)
                if prev is not None and step > prev + 1:
                    # the trainer replays only its CURRENT round, so any
                    # round between the restored snapshot and the stream
                    # is unrecoverable.  A gap of exactly ONE round is
                    # the unavoidable async-write race (the kill landed
                    # after _run_round but before its background
                    # snapshot hit disk): tolerate it LOUDLY — counted
                    # and printed, never silent.  A wider gap means the
                    # configuration itself discards rounds on every
                    # restore (checkpoint_every > 1, or snapshots
                    # repeatedly failing to land) — fail the job rather
                    # than quietly train past several lost updates.
                    lost = step - prev - 1
                    if lost > 1:
                        raise RuntimeError(
                            "incarnation fence gap: trainer %d is at "
                            "step %d but this server last folded step %d "
                            "— the restored checkpoint is missing %d "
                            "intermediate rounds that cannot be replayed "
                            "(trainers only record the current round); "
                            "refusing to silently drop them.  Lower "
                            "checkpoint_every so restores stay within "
                            "one round of the stream." % (tid, step, prev,
                                                          lost))
                    if self._send_step.get(tid) != step:
                        # count once per lost round, not once per
                        # arriving bucket of the gapped step (the reset
                        # below stamps _send_step before bucket 2)
                        self.counters["lost_rounds"] += 1
                        print("PSERVER LOST-ROUND trainer=%d step=%d "
                              "folded=%d: the kill raced the background "
                              "checkpoint write; one round's update is "
                              "lost" % (tid, step, prev), flush=True)
                if self._send_step.get(tid) != step:
                    self._send_step[tid] = step
                    self._send_seen[tid] = set()
            for name, value in blocks.items():
                self._fold_pending_locked(name, tid, np.asarray(value))
            if not seq_total:
                return self._plan_reply_locked({"ok": True})
            if step is not None:
                seen = self._send_seen[tid]
                seen.add(int(seq_idx or 0))
                if len(seen) < int(seq_total):
                    return self._plan_reply_locked({"ok": True})
                if sparse_tables:
                    # the trainer declared sparse chunks for this step:
                    # every one must be PENDING before the fold may run
                    # the round.  A crash between the sparse acks and
                    # the dense folds re-delivers only the (unacked)
                    # dense buckets via RPC retries — folding then would
                    # run the round without its sparse rows and the
                    # fence would drop the corrective replay as
                    # dup_round.  Refuse (keeping the assembled set);
                    # the fenced replay re-queues sparse first, and its
                    # re-shipped dense buckets re-trigger this check.
                    unknown = [t for t in sparse_tables
                               if t not in self.sparse_tables]
                    if unknown:
                        raise KeyError(
                            "send_bucket declares sparse tables this "
                            "server does not shard: %s" % unknown)
                    missing = [t for t in sparse_tables
                               if (tid, t) not in self._pending_sparse]
                    if missing:
                        return self._plan_reply_locked(
                            {"ok": True, "need_sparse": missing})
                self._folded_send[tid] = step
                self._send_step.pop(tid, None)
                self._send_seen.pop(tid, None)
            else:  # legacy count-based fold (pre-fencing callers)
                c = self._send_bucket_counts.get(tid, 0) + 1
                if c < int(seq_total):
                    self._send_bucket_counts[tid] = c
                    return {"ok": True}
                self._send_bucket_counts[tid] = 0
            # last bucket of this trainer's step: its send barrier
            self._send_barriers.add(trainer_id)
            if len(self._send_barriers) >= len(self._live):
                self._run_round()
            else:
                rnd = self._round
                self._cv.wait_for(
                    lambda: self._round > rnd or self._done.is_set()
                    or tid in self._evicted
                )
                if tid in self._evicted:
                    return {"ok": False, "evicted": True}
            # the blocking (folded-barrier) reply is constructed AFTER
            # the round ran — a boundary-minted epoch rides it, so every
            # survivor learns the new world exactly one round after the
            # membership change
            return self._plan_reply_locked({"ok": True})
        return {"ok": True}

    def _h_get_bucket(self, names, trainer_id=0, fetch_total=None,
                      step=None, seq_idx=None, wire_dtype=None):
        """Coalesced param fetch: one frame returns every requested block
        — and in sync mode ONE params-ready wait covers the whole bucket
        instead of one blocking round trip per variable.  `fetch_total`
        folds the fetch barrier in: when this trainer's last declared
        bucket has been served (any arrival order) it counts as the
        trainer's fetch barrier, and the round resets once every live
        trainer got theirs.  `step`/`seq_idx` mirror _h_send_bucket's
        fencing: a replayed fetch stream counts by set (never double-
        folds), and a fetch step this server already folded is served
        (reads are harmless) without counting.  `wire_dtype` (the
        REQUESTER's declaration, stamped into its bucket plan by the
        transpiler) compresses float blocks in the reply —
        'bfloat16' halves every param frame; the client decodes back
        to the original dtype (rpc.Bf16Wire).

        A fetch naming a MIGRATED-AWAY block (the sender's layout
        predates a committed handoff) answers stale_plan — the client
        re-plans and re-pulls from the new owner — instead of a
        KeyError crash.  Checked BEFORE the params wait: a stale fetch
        must return now, not park on a round that will never serve
        it."""
        if self.plan_spec is not None:
            gone = [n for n in names if n in self._dropped_vars]
            if gone:
                with self._cv:
                    self.counters["stale_plan_drops"] += 1
                    return self._plan_reply_locked(
                        {"stale_plan": True,
                         "pepoch": self._plan_epoch})
        if self.sync_mode:
            with self._cv:
                self._touch(trainer_id)
                # a REPLAYED fetch of a step this trainer already folded
                # (restart recovery) is served from the current params
                # without waiting: its own fold may have flipped
                # params_ready off, and parking here would deadlock the
                # replay on a flag only the next round sets
                already_folded = (
                    step is not None
                    and int(step) <= self._folded_fetch.get(
                        int(trainer_id), -1))
                if not already_folded:
                    self._cv.wait_for(
                        lambda: self._params_ready or self._done.is_set()
                    )
                if int(trainer_id) in self._evicted:
                    raise RuntimeError(
                        "trainer %s was evicted from the sync round; "
                        "params reflect a round it did not participate "
                        "in — restart the trainer to rejoin"
                        % (trainer_id,))
        out = {}
        for name in names:
            var = self.scope.find_var(name)
            if var is None:
                raise KeyError("pserver has no var %s" % name)
            out[name] = np.asarray(var)
        if wire_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                "get_bucket: unknown wire_dtype %r" % (wire_dtype,))
        if wire_dtype == "bfloat16":
            from .rpc import Bf16Wire

            out = {n: (Bf16Wire(v) if v.dtype.kind == "f" else v)
                   for n, v in out.items()}
        if self.sync_mode and fetch_total:
            with self._cv:
                tid = int(trainer_id)
                if tid in self._evicted:
                    # evicted between the params wait and here: a ghost
                    # must not count toward the survivors' fetch barrier
                    raise RuntimeError(
                        "trainer %s was evicted from the sync round"
                        % (trainer_id,))
                if step is not None:
                    step = int(step)
                    if step <= self._folded_fetch.get(tid, -1):
                        return out  # replay of a folded fetch: serve only
                    if self._fetch_step.get(tid) != step:
                        self._fetch_step[tid] = step
                        self._fetch_seen[tid] = set()
                    seen = self._fetch_seen[tid]
                    seen.add(int(seq_idx or 0))
                    if len(seen) < int(fetch_total):
                        return out
                    self._folded_fetch[tid] = step
                    self._fetch_step.pop(tid, None)
                    self._fetch_seen.pop(tid, None)
                else:  # legacy count-based fold
                    c = self._fetch_bucket_counts.get(tid, 0) + 1
                    if c < int(fetch_total):
                        self._fetch_bucket_counts[tid] = c
                        return out
                    self._fetch_bucket_counts[tid] = 0
                self._fetch_barriers.add(trainer_id)
                if len(self._fetch_barriers) >= len(self._live):
                    self._complete_fetch_barrier_locked()
        return out

    def _h_barrier(self, kind, trainer_id=0):
        if not self.sync_mode:
            return {"ok": True}
        with self._cv:
            self._touch(trainer_id)
            if int(trainer_id) in self._evicted:
                return {"ok": False, "evicted": True}
            if kind == "send":
                self._send_barriers.add(trainer_id)
                if len(self._send_barriers) >= len(self._live):
                    self._run_round()
                else:
                    rnd = self._round
                    tid = int(trainer_id)
                    self._cv.wait_for(
                        lambda: self._round > rnd or self._done.is_set()
                        or tid in self._evicted
                    )
                    if tid in self._evicted:
                        # evicted WHILE blocked here (round moved on, or
                        # will, without our grads): report it now, not
                        # one stale step later
                        return {"ok": False, "evicted": True}
            elif kind == "fetch":
                self._fetch_barriers.add(trainer_id)
                if len(self._fetch_barriers) >= len(self._live):
                    self._complete_fetch_barrier_locked()
        return {"ok": True}

    def _h_get(self, name, trainer_id=0):
        if self.sync_mode:
            with self._cv:
                self._touch(trainer_id)
                self._cv.wait_for(
                    lambda: self._params_ready or self._done.is_set()
                )
                if int(trainer_id) in self._evicted:
                    raise RuntimeError(
                        "trainer %s was evicted from the sync round; "
                        "params reflect a round it did not participate "
                        "in — restart the trainer to rejoin"
                        % (trainer_id,))
        var = self.scope.find_var(name)
        if var is None:
            raise KeyError("pserver has no var %s" % name)
        return np.asarray(var)

    # ---- sparse embedding shards (distributed lookup table) -------------
    def _h_prefetch(self, table, ids, trainer_id=0, clock=None):
        """Serve embedding rows by local row id (prefetch_op analog).
        `clock` (async fenced mode) is the requesting trainer's logical
        clock: a lookup from a trainer past the staleness bound parks
        here — the read side of the bound, so a fast trainer cannot even
        OBSERVE rows more than `bound` steps ahead of the laggard.

        A migrated-away shard answers a stale_plan DICT instead of rows
        (never a KeyError crash): the client re-plans and re-reads from
        the shard's new owner."""
        if self.plan_spec is not None and table not in self.sparse_tables:
            with self._cv:
                self.counters["stale_plan_drops"] += 1
                return self._plan_reply_locked(
                    {"stale_plan": True, "pepoch": self._plan_epoch})
        tbl = self.sparse_tables[table]["tbl"]
        ids = np.asarray(ids).reshape(-1)
        ids = np.clip(ids, 0, tbl.shape[0] - 1)
        with self._cv:
            if clock is not None and not self.sync_mode:
                tid = int(trainer_id)
                self._touch(tid)
                self._clock_update_locked(tid, clock)
                self._park_if_stale_locked(tid, clock)
            return tbl[ids].copy()

    def _sparse_lr_value(self, info):
        """Current learning rate for a sparse table: the scheduled lr var
        from the pserver scope (decayed by lr_program) when named, else
        the captured constant, else the server-wide fallback.  A
        SCHEDULED lr (named var, no constant) whose var is missing is an
        error — silently training at a stale constant is the failure the
        old NotImplementedError guard existed to prevent."""
        opt = info.get("opt") or {}
        name = opt.get("lr_name")
        if name:
            var = self.scope.find_var(name)
            if var is not None:
                return (float(np.asarray(var).reshape(-1)[0])
                        * float(opt.get("lr_scale", 1.0)))
            if info.get("lr") is None:
                raise RuntimeError(
                    "sparse table optimizer needs scheduled lr var %r but "
                    "the pserver scope does not hold it (lr_program split "
                    "miss?) and no constant fallback was captured" % name)
        if info.get("lr") is not None:
            return float(info["lr"])
        return float(self.sparse_lr)

    def _advance_pows(self, info):
        """Advance an adam table's beta pows by one step (no-op for
        non-adam tables or before the first application created them)."""
        opt = info.get("opt") or {}
        if opt.get("type") != "adam":
            return
        at = opt.get("attrs") or {}
        b1 = float(at.get("beta1", 0.9))
        b2 = float(at.get("beta2", 0.999))
        info["beta1_pow"] = info.get("beta1_pow", b1) * b1
        info["beta2_pow"] = info.get("beta2_pow", b2) * b2

    def _apply_sparse(self, table, ids, rows, advance_pows=True):
        """One optimizer application on this shard's touched rows
        (SelectedRows semantics: duplicates merged first — the moment
        updates are non-linear in g).  Mirrors the lazy/sparse branches
        of ops/optimizer_ops.py so a dist run matches the local
        is_sparse run row for row.  Called under self._lock.
        advance_pows=False defers the adam beta-pow advance to the
        caller (sync rounds advance once per round for EVERY table via
        _advance_pows, even row-less ones)."""
        info = self.sparse_tables[table]
        tbl = info["tbl"]
        opt = info.get("opt") or {}
        typ = opt.get("type", "sgd")
        at = opt.get("attrs") or {}
        ids = np.asarray(ids).reshape(-1)
        dirty = self._mig_dirty
        if dirty is not None and table in dirty:
            # delta handoff in flight: record which rows this (still
            # serving) application touches so the frozen final tail
            # ships only them.  Momentum's densified rule mutates EVERY
            # row (whole-table velocity decay) — fall back to a full
            # re-ship rather than under-ship.
            if typ == "momentum":
                dirty[table] = None
            elif dirty[table] is not None:
                dirty[table].update(int(x) for x in ids)
        # explicit second dim: -1 is ambiguous (ValueError) for 0 rows,
        # and rowless momentum decay feeds exactly that
        rows = np.asarray(rows, dtype=tbl.dtype).reshape(
            ids.size, tbl.shape[1])
        uids, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((uids.size, tbl.shape[1]), tbl.dtype)
        np.add.at(g, inv, rows)
        lr = self._sparse_lr_value(info)
        if typ == "sgd":
            tbl[uids] -= lr * g
        elif typ == "adagrad":
            eps = float(at.get("epsilon", 1e-6))
            m = info.setdefault("moment", np.zeros_like(tbl))
            mn = m[uids] + g * g
            m[uids] = mn
            tbl[uids] -= lr * g / (np.sqrt(mn) + eps)
        elif typ == "momentum":
            # momentum_op.h SparseMomentumFunctor: densified rule over
            # EVERY shard row — untouched rows' velocity still decays
            mu = float(at.get("mu", 0.9))
            v = info.setdefault("velocity", np.zeros_like(tbl))
            g_dense = np.zeros_like(tbl)
            g_dense[uids] = g
            v *= mu
            v += g_dense
            if at.get("use_nesterov"):
                tbl -= lr * (g_dense + mu * v)
            else:
                tbl -= lr * v
        elif typ == "adam":
            b1 = float(at.get("beta1", 0.9))
            b2 = float(at.get("beta2", 0.999))
            eps = float(at.get("epsilon", 1e-8))
            m1 = info.setdefault("moment1", np.zeros_like(tbl))
            m2 = info.setdefault("moment2", np.zeros_like(tbl))
            b1p = info.setdefault("beta1_pow", b1)
            b2p = info.setdefault("beta2_pow", b2)
            lr_t = lr * np.sqrt(1.0 - b2p) / (1.0 - b1p)
            m1n = b1 * m1[uids] + (1.0 - b1) * g
            m2n = b2 * m2[uids] + (1.0 - b2) * g * g
            m1[uids], m2[uids] = m1n, m2n
            tbl[uids] -= lr_t * m1n / (np.sqrt(m2n) + eps)
            if advance_pows:
                # async mode: global beta pows advance per application
                # (the lazy adam rule, adam_op.h SelectedRows branch)
                info["beta1_pow"] = b1p * b1
                info["beta2_pow"] = b2p * b2
        else:
            raise ValueError("unknown sparse optimizer %r" % typ)

    def _h_send_sparse(self, table, ids, rows, trainer_id=0, step=None,
                       seq=None, pepoch=None):
        """Sparse optimizer update on this server's rows (SelectedRows
        grad).  Sync mode queues until the round barrier so the update
        sees this round's scheduled lr and all trainers' rows merge into
        ONE application (the reference's optimizer-sub-block-at-barrier
        semantics); async applies immediately.  `step` is the sync dense
        stream's fence token: a fenced replay of a round this server
        already folded (it survived in the restored snapshot) is dropped
        so its rows cannot leak into the NEXT round.

        `seq` (async fenced delivery, docs/FAULT_TOLERANCE.md): the
        per-(trainer, table) sequence token the transpiler-stamped async
        ops mint once per STEP (shipped to every server, empty chunks
        included, so seq doubles as the trainer's logical clock).  The
        fence is monotonic — sends are serial per trainer, so a seq at
        or below the durably-applied high-water is an at-least-once
        re-delivery and drops (`dup`); the reply acks the high-water so
        the client can prune its resend queue.  Applied non-empty chunks
        are journaled + fsync'd BEFORE the ack, making ack == durable.
        The seq also drives the bounded-staleness park: a trainer
        running more than FLAGS_async_staleness_bound ahead of the
        slowest live peer waits here until the laggard advances or
        departs."""
        ids = np.asarray(ids).reshape(-1)
        rows = np.asarray(rows)
        with self._cv:
            self._touch(trainer_id)
            self._freeze_wait_locked()
            tid = int(trainer_id)
            if tid in self._evicted:
                return {"ok": False, "evicted": True}
            if self.plan_spec is not None \
                    and table not in self.sparse_tables:
                # migrated-away sparse shard: the sender's routing
                # predates the flip — re-plan and re-ship to the owner
                self.counters["stale_plan_drops"] += 1
                return self._plan_reply_locked(
                    {"ok": True, "stale_plan": True,
                     "pepoch": self._plan_epoch})
            if self.sync_mode and self._stale_plan_locked(pepoch):
                # plan-epoch fence: rows scaled for a stale world must
                # not queue into a current-epoch round (the sender
                # re-plans and re-ships — see _h_send_bucket)
                return {"ok": True, "stale_plan": True,
                        "pepoch": self._plan_epoch}
            if (self.sync_mode and step is not None
                    and int(step) <= self._folded_send.get(tid, -1)):
                self.counters["dup_round_drops"] += 1
                return self._plan_reply_locked(
                    {"ok": True, "dup_round": True})
            if self.sync_mode:
                # keyed overwrite: a fenced replay of this round's chunk
                # replaces rather than double-queues (dist_ops ships one
                # chunk per (table, server) per step)
                self._pending_sparse[(tid, table)] = (ids, rows)
                return self._plan_reply_locked({"ok": True})
            # ---- async path ------------------------------------------
            key = (tid, str(table))
            if seq is not None:
                seq = int(seq)
                fence = self._sparse_fence.get(key, 0)
                if seq <= fence:
                    self.counters["dedup_drops"] += 1
                    return self._plan_reply_locked(
                        {"ok": True, "dup": True, "acked": fence})
                self._clock_update_locked(tid, seq)
                self._park_if_stale_locked(tid, seq)
                if tid in self._evicted:  # evicted while parked
                    return {"ok": False, "evicted": True}
            if ids.size:
                self._async_touched.add(table)
                self._apply_sparse(table, ids, rows)
                # durable BEFORE the ack; empty (clock-only) chunks skip
                # the journal — the fence is monotonic, so the restored
                # high-water tolerating their seq gap is safe
                self._journal_append_locked(
                    {"k": "s", "t": str(table), "i": ids, "r": rows,
                     "tid": tid, "q": seq})
            if seq is not None:
                # fence commit BEFORE the rotation check: a snapshot
                # capturing the applied chunk but not its fence would
                # let a re-delivery through post-restore (double apply)
                self._sparse_fence[key] = seq
            # rotation cadence runs for EVERY journaled chunk — unfenced
            # (hybrid-collective / legacy) streams journal too, and with
            # dense traffic riding the mesh nothing else would ever
            # bound the segment's growth
            self._journal_maybe_snapshot_locked()
            if seq is not None:
                return self._plan_reply_locked({"ok": True, "acked": seq})
        return {"ok": True}

    def _h_sparse_clocks(self, clocks, trainer_id=0):
        """Merged clock-only frame (async fenced mode): one RPC carries
        EVERY table whose chunk this step had no rows for this server —
        previously each shipped its own empty send_sparse, n_servers *
        n_tables tiny frames per async step.  Semantics are identical to
        the empty chunks this replaces: per-table fences advance
        monotonically (nothing is journaled — there is no data), the
        trainer's logical clock advances to the newest seq, and the
        bounded-staleness park applies exactly once for the frame."""
        with self._cv:
            self._touch(trainer_id)
            self._freeze_wait_locked()
            tid = int(trainer_id)
            if tid in self._evicted:
                return {"ok": False, "evicted": True}
            newest = 0
            for table, seq in sorted(dict(clocks).items()):
                key = (tid, str(table))
                seq = int(seq)
                if seq > self._sparse_fence.get(key, 0):
                    self._sparse_fence[key] = seq
                newest = max(newest, seq)
            if newest:
                self._clock_update_locked(tid, newest)
                self._park_if_stale_locked(tid, newest)
                if tid in self._evicted:  # evicted while parked
                    return {"ok": False, "evicted": True}
            return self._plan_reply_locked({"ok": True, "acked": newest})

    def _h_checkpoint_notify(self, dir=None, trainer_id=0):
        """Trainer-initiated checkpoint (checkpoint_notify_op.cc analog).
        Snapshots into the REQUESTED dir without adopting it — the
        server's own periodic checkpoints keep their configured home, so
        they never overwrite (or resurrect) a trainer serial dir."""
        with self._lock:
            ok = self.save_checkpoint(dir=dir)
        return {"ok": bool(ok), "round": self._round}

    def _h_complete(self, trainer_id=0):
        with self._cv:
            tid = int(trainer_id)
            departed = False
            if tid in self._live:
                self._live.discard(tid)
                self._completed.add(tid)
                departed = True
            elif (tid not in self._evicted and tid not in self._completed
                    and self._live):
                # genuinely unknown id (legacy callers used a bare
                # count): treat it as one departure so done-detection
                # still converges.  A REPEATED complete (trainer exits
                # after send_complete_all, launcher also notifies) and an
                # evicted trainer's complete are already accounted for —
                # popping an arbitrary survivor would corrupt the barrier
                # denominator.
                self._live.pop()
                self._completed.add(tid)  # once: repeats must not re-pop
                departed = True
            self._tracked.pop(tid, None)
            # completion frees the staleness bound exactly like eviction
            # (the notify_all below wakes any parked fast peer)
            self._trainer_clock.pop(tid, None)
            # a departing trainer may unblock a pending round.  Its SEND
            # entry is kept (a clean departure's grads still count toward
            # the round it joined) but its FETCH entry is dropped: "I
            # already fetched" must not complete the fetch count while
            # survivors are still mid-fetch — that would reset
            # params_ready under their remaining gets
            self._fetch_barriers.discard(tid)
            self._send_bucket_counts.pop(tid, None)
            self._fetch_bucket_counts.pop(tid, None)
            self._send_step.pop(tid, None)
            self._send_seen.pop(tid, None)
            self._fetch_step.pop(tid, None)
            self._fetch_seen.pop(tid, None)
            # a parked joiner admits (boundary-guarded) before the
            # done-check: a completing survivor must not declare the job
            # over under a rejoiner
            self._admit_pending_joins_locked()
            if departed:
                # clean departure is a durable shrink: the survivors'
                # next rounds re-scale to the smaller world
                self._mark_plan_dirty_locked()
            if not self._live:
                self._done.set()
            if self.sync_mode and self._live:
                self._reeval_barriers_locked()
            self._cv.notify_all()
        return {"ok": True}

    @property
    def _live_trainers(self):
        """Back-compat count view of the live set."""
        return len(self._live)

    def wait_done(self, timeout=None):
        return self._done.wait(timeout)


def run_pserver(program, scope, executor=None):
    """Execute a transpiled pserver program: start the VarServer on the
    listen_and_serv op's endpoint, block until all trainers complete.

    This is what Executor.run does when it sees a `listen_and_serv` op —
    the analog of ListenAndServOp::RunImpl.
    """
    from .rpc import make_var_server

    listen_op = None
    for op in program.global_block().ops:
        if op.type == "listen_and_serv":
            listen_op = op
            break
    assert listen_op is not None, "no listen_and_serv op in pserver program"
    a = listen_op.attrs

    shard_programs = [framework.Program.from_json(s) for s in a["optimize_programs"]]
    lr_program = (
        framework.Program.from_json(a["lr_program"]) if a.get("lr_program") else None
    )

    # materialize block vars from the full vars the startup program created
    for src, block_name, begin, end in a["slice_plan"]:
        var = scope.find_var(src)
        if var is None:
            raise RuntimeError(
                "pserver startup did not create %s (run get_startup_program "
                "through this executor first)" % src
            )
        flat = np.asarray(var).reshape(-1)
        scope.set(block_name, np.ascontiguousarray(flat[begin:end]))
    for name in a.get("whole_vars", []):
        if scope.find_var(name) is None:
            raise RuntimeError("pserver startup did not create %s" % name)

    # distributed lookup-table shards: slice this server's rows (g%N) out
    # of the full table the startup program initialized.  Spec row:
    # [shard, src, server_idx, n_servers, lr] (+ optional opt dict)
    sparse_tables = {}
    for spec in a.get("sparse_tables", []):
        shard_name, src, server_idx, n_servers, lr = spec[:5]
        opt = spec[5] if len(spec) > 5 else None
        var = scope.find_var(src)
        if var is None:
            raise RuntimeError(
                "pserver startup did not create lookup table %s" % src
            )
        full = np.array(var)
        sparse_tables[shard_name] = {
            "tbl": np.ascontiguousarray(full[int(server_idx)::int(n_servers)]),
            "lr": float(lr) if lr is not None else None,
            "opt": dict(opt) if opt else {"type": "sgd", "attrs": {}},
        }

    import os as _os

    # checkpoint wiring: attr from the transpiler config, else the
    # PADDLE_PSERVER_CKPT_DIR env contract (test/ops harness)
    ckpt_dir = a.get("checkpoint_dir") or _os.environ.get(
        "PADDLE_PSERVER_CKPT_DIR"
    )
    ckpt_every = int(
        a.get("checkpoint_every")
        or _os.environ.get("PADDLE_PSERVER_CKPT_EVERY", 1)
    )
    try:
        server_idx = [s.strip() for s in _os.environ.get(
            "PADDLE_PSERVER_EPS", ""
        ).split(",")].index(a["endpoint"])
    except ValueError:
        if a.get("elastic"):
            # elastic-grown server OUTSIDE the base endpoint list: its
            # checkpoint/journal files must not collide with base
            # server 0's — key them by port (unique per live server)
            server_idx = int(a["endpoint"].rsplit(":", 1)[1])
        else:
            server_idx = 0

    # live shard migration config: the declarative plan spec (when the
    # transpiler stamped one) + this server's endpoint + the pserver
    # world — PADDLE_PSERVER_EPS is the BASE world; a snapshot restore
    # or a migrate_commit moves it forward
    plan_spec = a.get("plan_spec")
    ps_world = [e.strip() for e in _os.environ.get(
        "PADDLE_PSERVER_EPS", "").split(",") if e.strip()]
    if not ps_world and plan_spec:
        ps_world = list(plan_spec.get("endpoints") or [])
    sparse_shard_idx = {spec[0]: int(spec[2])
                       for spec in a.get("sparse_tables", [])}

    service = ParameterServer(
        shard_programs,
        dict(a["grad_to_shard"]),
        lr_program=lr_program,
        num_trainers=int(a["trainers"]),
        sync_mode=bool(a["sync_mode"]),
        scope=scope,
        sparse_tables=sparse_tables,
        sparse_lr=float(a.get("sparse_lr", 0.01)),
        checkpoint_dir=ckpt_dir,
        checkpoint_every=ckpt_every,
        server_idx=server_idx,
        plan_spec=plan_spec,
        endpoint=a["endpoint"],
        ps_world=ps_world or None,
        sparse_shard_idx=sparse_shard_idx,
    )
    if (service._journal_enabled() and plan_spec
            and int((plan_spec.get("flags") or {})
                    .get("comm_bucket_bytes", 0)) <= 0):
        import sys as _sys

        # satellite: surface the reduced guarantee at STARTUP, not just
        # in the docs — the legacy per-var wire journals but cannot
        # fence, so exactly-once across SIGKILL does not hold here
        service._unfenced_async = True
        _sys.stderr.write(
            "PSERVER WARNING: async journal armed on the legacy "
            "per-variable wire (comm_bucket_bytes=0): applied updates "
            "are crash-durable but UNFENCED — an RPC retry straddling "
            "a restart can double-apply.  Set FLAGS_comm_bucket_bytes>0 "
            "for exactly-once delivery (docs/FAULT_TOLERANCE.md)\n")
    restored = service.load_checkpoint()
    if restored is not None:
        print("PSERVER RESTORED round=%d incarnation=%d"
              % (restored, service.incarnation), flush=True)
    elif service._journal_enabled():
        # journal armed, cold start: land a BIRTH snapshot (synchronous,
        # before the listener opens, so no update can precede it).  The
        # journal records deltas; without a persisted base a restore
        # before the first cadence snapshot would replay them onto a
        # freshly re-initialized table — only bit-identical to the dead
        # incarnation's when the startup init happens to be seeded.
        service.save_checkpoint()
    server = make_var_server(a["endpoint"], service).start()
    try:
        service.wait_done()
    finally:
        server.shutdown()
        # recovery observability: the server-side sibling of the
        # trainers' COUNTERS line (distinct prefix — bench.py sums
        # trainer COUNTERS lines and must not fold these in)
        import json as _json

        with service._cv:
            phases = service._phases_snapshot_locked()
            plan_epoch = service._plan_epoch
        print("PSERVER-STATS " + _json.dumps(
            dict(service.counters, round=service._round,
                 incarnation=service.incarnation,
                 async_sends=service._async_sends,
                 plan_epoch=plan_epoch, phases=phases)), flush=True)
