"""Fault-tolerant task-queue master (go/master/service.go re-design).

The reference's Go master keeps a queue of data tasks in etcd: trainers
GetTask/TaskFinished/TaskFailed, tasks time out and re-queue when a
trainer dies, repeated failures discard a task, and state snapshots let a
restarted master resume (SetDataset :280, GetTask :368, TaskFailed :455,
timeout re-queue :341, snapshot :207).

TPU-native re-homing (SURVEY §5.3): same protocol over the framework's
TCP RPC with a JSON file snapshot standing in for etcd — the coordination
backbone for elastic data dispatch across trainer hosts.
"""

import json
import os
import threading
import time

from .rpc import RPCClient, VarServer


class Task:
    def __init__(self, task_id, payload):
        self.id = task_id
        self.payload = payload
        self.failures = 0
        self.deadline = 0.0  # while pending

    def to_dict(self):
        return {"id": self.id, "payload": self.payload, "failures": self.failures}

    @staticmethod
    def from_dict(d):
        t = Task(d["id"], d["payload"])
        t.failures = d.get("failures", 0)
        return t


class MasterService:
    """Service object for rpc.VarServer."""

    def __init__(self, timeout_s=60.0, failure_max=3, snapshot_path=None,
                 chunks_per_task=1, snapshot_interval_s=1.0):
        self.timeout_s = timeout_s
        self.failure_max = failure_max
        self.snapshot_path = snapshot_path
        self.chunks_per_task = max(1, chunks_per_task)
        self.snapshot_interval_s = snapshot_interval_s
        self._last_snapshot = 0.0
        self._dataset_set = False
        self._lock = threading.Lock()
        self._todo = []      # [Task]
        self._pending = {}   # task_id -> Task (leased)
        self._done = []      # [Task]
        self._next_id = 0
        self._epoch_done = threading.Event()
        if snapshot_path and os.path.exists(snapshot_path):
            self._load_snapshot()

    # ---- snapshot (etcd stand-in, service.go:207) ---------------------
    def _save_snapshot(self, force=False):
        """Throttled (ticker-style, like the reference master) — at most one
        write per snapshot_interval_s unless `force` (epoch boundaries,
        dataset set).  Worst case a restart replays < interval of leases."""
        if not self.snapshot_path:
            return
        now = time.time()
        epoch_boundary = not self._todo and not self._pending
        if (
            not force
            and not epoch_boundary
            and now - self._last_snapshot < self.snapshot_interval_s
        ):
            return
        self._last_snapshot = now
        state = {
            "todo": [t.to_dict() for t in self._todo],
            "pending": [t.to_dict() for t in self._pending.values()],
            "done": [t.to_dict() for t in self._done],
            "next_id": self._next_id,
            "dataset_set": self._dataset_set,
        }
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)

    def _load_snapshot(self):
        """Resume from the etcd-stand-in snapshot; a corrupt/unreadable
        file means a COLD start with a warning — a restarting master must
        come up, never crash-loop on a torn write (the same discipline as
        the pserver's crc-checked checkpoints)."""
        import sys

        try:
            with open(self.snapshot_path) as f:
                state = json.load(f)
            todo = [Task.from_dict(d) for d in state["todo"]]
            pending = [Task.from_dict(d) for d in state["pending"]]
            done = [Task.from_dict(d) for d in state["done"]]
            next_id = state["next_id"]
        except Exception as e:
            # not just JSON errors: valid-but-wrong-shaped JSON raises
            # TypeError/AttributeError in Task.from_dict — any failure
            # here must mean a cold start, never a crash loop
            sys.stderr.write(
                "MASTER snapshot %s unusable, starting cold: %s\n"
                % (self.snapshot_path, e))
            return
        # leased tasks from the dead master go back to todo
        self._todo = todo + pending
        self._done = done
        self._next_id = next_id
        self._dataset_set = state.get("dataset_set", bool(self._todo or self._done))

    # ---- verbs ---------------------------------------------------------
    def handle(self, verb, **kw):
        try:
            return getattr(self, "_h_" + verb)(**kw)
        except Exception as e:
            import traceback

            return {"__error__": "%s\n%s" % (e, traceback.format_exc())}

    def _requeue_timeouts_locked(self):
        now = time.time()
        changed = False
        for tid in [t for t, task in self._pending.items() if task.deadline < now]:
            task = self._pending.pop(tid)
            task.failures += 1
            changed = True
            if task.failures >= self.failure_max:
                continue  # discarded (service.go failureMax)
            self._todo.append(task)
        return changed

    def _h_set_dataset(self, chunks, trainer_id=0):
        """Partition chunks into tasks (SetDataset :280).  Idempotent per
        epoch: once a dataset is set, later set_dataset calls (slow-starting
        trainers, retries — even after the epoch drained) are no-ops until
        new_epoch() resets."""
        with self._lock:
            if self._dataset_set or self._todo or self._pending:
                return {"ok": True, "already_set": True}
            self._dataset_set = True
            created = 0
            group = []
            for c in chunks:
                group.append(c)
                if len(group) >= self.chunks_per_task:
                    self._todo.append(Task(self._next_id, group))
                    self._next_id += 1
                    created += 1
                    group = []
            if group:
                self._todo.append(Task(self._next_id, group))
                self._next_id += 1
                created += 1
            self._epoch_done.clear()
            self._save_snapshot(force=True)
        return {"ok": True, "num_tasks": created}

    def _h_get_task(self, trainer_id=0):
        """Lease a task (GetTask :368); {} when none available."""
        with self._lock:
            if self._requeue_timeouts_locked():
                # timeouts/discards are durable state: persist them even on
                # the empty-queue paths, or a master restart would resurrect
                # discarded tasks from the stale snapshot
                self._save_snapshot()
            if not self._todo:
                if not self._pending:
                    self._epoch_done.set()
                    return {"task": None, "epoch_done": True}
                return {"task": None, "epoch_done": False}
            task = self._todo.pop(0)
            task.deadline = time.time() + self.timeout_s
            self._pending[task.id] = task
            self._save_snapshot()
            return {"task": {"id": task.id, "payload": task.payload}}

    def _h_task_finished(self, task_id, trainer_id=0):
        with self._lock:
            task = self._pending.pop(task_id, None)
            if task is not None:
                self._done.append(task)
            if not self._todo and not self._pending:
                self._epoch_done.set()
            self._save_snapshot()
        return {"ok": True}

    def _h_task_failed(self, task_id, trainer_id=0):
        """Explicit failure: requeue unless failure_max hit (TaskFailed :455)."""
        with self._lock:
            task = self._pending.pop(task_id, None)
            if task is not None:
                task.failures += 1
                if task.failures < self.failure_max:
                    self._todo.append(task)
            self._save_snapshot()
        return {"ok": True}

    def _h_new_epoch(self, trainer_id=0):
        """Reset for the next epoch (rank-0 trainer calls this, then
        set_dataset again)."""
        with self._lock:
            self._todo = []
            self._pending = {}
            self._done = []
            self._dataset_set = False
            self._save_snapshot(force=True)
        return {"ok": True}

    def _h_num_done(self, trainer_id=0):
        with self._lock:
            return {
                "done": len(self._done),
                "todo": len(self._todo),
                "pending": len(self._pending),
            }


class Master:
    """In-process master bootstrap: serve on an endpoint."""

    def __init__(self, endpoint, timeout_s=60.0, failure_max=3,
                 snapshot_path=None, chunks_per_task=1):
        self.service = MasterService(
            timeout_s, failure_max, snapshot_path, chunks_per_task
        )
        self.server = VarServer(endpoint, self.service).start()
        self.endpoint = self.server.endpoint

    def shutdown(self):
        self.server.shutdown()


class MasterClient:
    """Trainer-side client (go/pserver/client role for the master)."""

    def __init__(self, endpoint, trainer_id=0):
        self._cli = RPCClient.get(endpoint)
        self.trainer_id = trainer_id

    def set_dataset(self, chunks):
        return self._cli.call("set_dataset", chunks=list(chunks),
                              trainer_id=self.trainer_id)

    def get_task(self):
        """Returns (task_id, payload), or (None, None) when nothing is
        leasable right now; check epoch_done()/stats() to distinguish a
        drained epoch from tasks pending on other trainers."""
        r = self._cli.call("get_task", trainer_id=self.trainer_id)
        self._last_epoch_done = bool(r.get("epoch_done", False))
        if r.get("task") is None:
            return None, None
        return r["task"]["id"], r["task"]["payload"]

    def epoch_done(self):
        """True when the last get_task saw an empty queue with no leases."""
        return getattr(self, "_last_epoch_done", False)

    def task_finished(self, task_id):
        return self._cli.call("task_finished", task_id=task_id,
                              trainer_id=self.trainer_id)

    def new_epoch(self):
        return self._cli.call("new_epoch", trainer_id=self.trainer_id)

    def task_failed(self, task_id):
        return self._cli.call("task_failed", task_id=task_id,
                              trainer_id=self.trainer_id)

    def stats(self):
        return self._cli.call("num_done", trainer_id=self.trainer_id)
