"""Unique name generator for variables/parameters.

TPU-native re-implementation of the naming utility the reference keeps in
``python/paddle/fluid/unique_name.py``: a process-wide counter per key plus a
``guard`` that layers use so parameter names like ``fc_0.w_0`` are stable and
collision-free across a program build.
"""

import contextlib
import threading


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.ids = {}
        self.prefix = prefix
        self._lock = threading.Lock()

    def __call__(self, key):
        with self._lock:
            if key not in self.ids:
                self.ids[key] = 0
            tmp = self.ids[key]
            self.ids[key] += 1
        return self.prefix + "_".join([key, str(tmp)])


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
