"""LoD (level-of-detail) ragged-sequence support, TPU-style.

The reference packs variable-length sequences without padding and carries a
nested offset index on every tensor (``lod_tensor.h:58,110``).  That layout
is hostile to XLA's static shapes, so the TPU-native design re-expresses
ragged batches as **padded dense data + per-sequence lengths** (equivalently
segment ids), the representation every sequence op lowers against
(SURVEY.md §5.7 "padded+masked or ragged-via-segment-ids").

``LoDTensor`` here is a host-side container: it accepts reference-style LoD
(offset lists) or raw nested python lists and materializes the padded array +
lengths that actually flow to the device.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "lengths_to_offsets", "offsets_to_lengths"]


def lengths_to_offsets(lengths):
    out = [0]
    for l in lengths:
        out.append(out[-1] + int(l))
    return out


def offsets_to_lengths(offsets):
    return [offsets[i + 1] - offsets[i] for i in range(len(offsets) - 1)]


class LoDTensor:
    """Padded data + recursive sequence lengths.

    `data`: np.ndarray of shape [batch, max_len, *feature] (level-1 LoD) or
    the raw dense array for lod_level=0.
    """

    def __init__(self, data, lod=None):
        self.data = np.asarray(data)
        # reference-style offsets per level
        self.lod = [list(l) for l in lod] if lod else []

    def lod_level(self):
        return len(self.lod)

    def seq_lens(self, level=0):
        if not self.lod:
            return np.full((self.data.shape[0],), self.data.shape[1], dtype=np.int32)
        return np.asarray(offsets_to_lengths(self.lod[level]), dtype=np.int32)

    def set_lod(self, lod):
        self.lod = [list(l) for l in lod]

    def shape(self):
        return self.data.shape

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.data.shape, self.lod)


def create_lod_tensor(data, recursive_seq_lens=None, place=None):
    """Build a padded LoDTensor from flat data + sequence lengths, or from a
    nested list of sequences (fluid.create_lod_tensor parity,
    python/paddle/fluid/lod_tensor.py)."""
    if isinstance(data, list) and data and isinstance(data[0], (list, np.ndarray)):
        seqs = [np.asarray(s) for s in data]
        lens = [len(s) for s in seqs]
        max_len = max(lens) if lens else 0
        feat = seqs[0].shape[1:] if seqs[0].ndim > 1 else ()
        out = np.zeros((len(seqs), max_len) + tuple(feat), dtype=seqs[0].dtype)
        for i, s in enumerate(seqs):
            out[i, : len(s)] = s
        return LoDTensor(out, [lengths_to_offsets(lens)])
    data = np.asarray(data)
    if recursive_seq_lens and len(recursive_seq_lens) > 2:
        raise NotImplementedError(
            "create_lod_tensor supports up to 2 LoD levels on TPU "
            "(got %d); flatten the outer nesting or pad by hand"
            % len(recursive_seq_lens)
        )
    if recursive_seq_lens and len(recursive_seq_lens) == 2:
        # nested (2-level) LoD: [doc -> #sentences, sentence -> #tokens]
        # padded as [docs, max_sents, max_toks, *feat] + both length arrays
        # (the re-expression of lod_tensor.h nested offsets; deeper nesting
        # composes the same way)
        doc_lens = list(recursive_seq_lens[0])
        tok_lens = list(recursive_seq_lens[1])
        if sum(doc_lens) != len(tok_lens):
            raise ValueError(
                "level-0 lengths sum to %d but there are %d level-1 "
                "sequences" % (sum(doc_lens), len(tok_lens))
            )
        if sum(tok_lens) != len(data):
            raise ValueError(
                "level-1 token lengths sum to %d but data has %d rows"
                % (sum(tok_lens), len(data))
            )
        max_sents = max(doc_lens) if doc_lens else 0
        max_toks = max(tok_lens) if tok_lens else 0
        feat = data.shape[1:]
        out = np.zeros(
            (len(doc_lens), max_sents, max_toks) + tuple(feat), dtype=data.dtype
        )
        tok_pad = np.zeros((len(doc_lens), max_sents), np.int32)
        ofs = 0
        si = 0
        for d, nsent in enumerate(doc_lens):
            for s in range(nsent):
                tl = tok_lens[si]
                out[d, s, :tl] = data[ofs:ofs + tl]
                tok_pad[d, s] = tl
                ofs += tl
                si += 1
        t = LoDTensor(
            out,
            [lengths_to_offsets(doc_lens), lengths_to_offsets(tok_lens)],
        )
        t.nested_seq_lens = tok_pad  # [docs, max_sents] per-sentence lengths
        return t
    if recursive_seq_lens:
        lens = list(recursive_seq_lens[-1])
        max_len = max(lens)
        feat = data.shape[1:]
        out = np.zeros((len(lens), max_len) + tuple(feat), dtype=data.dtype)
        ofs = 0
        for i, l in enumerate(lens):
            out[i, :l] = data[ofs : ofs + l]
            ofs += l
        return LoDTensor(out, [lengths_to_offsets(lens)])
    return LoDTensor(data)
