"""LoD (level-of-detail) ragged-sequence support, TPU-style.

The reference packs variable-length sequences without padding and carries a
nested offset index on every tensor (``lod_tensor.h:58,110``).  That layout
is hostile to XLA's static shapes, so the TPU-native design re-expresses
ragged batches as **padded dense data + per-sequence lengths** (equivalently
segment ids), the representation every sequence op lowers against
(SURVEY.md §5.7 "padded+masked or ragged-via-segment-ids").

``LoDTensor`` here is a host-side container: it accepts reference-style LoD
(offset lists) or raw nested python lists and materializes the padded array +
lengths that actually flow to the device.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "lengths_to_offsets", "offsets_to_lengths"]


def lengths_to_offsets(lengths):
    out = [0]
    for l in lengths:
        out.append(out[-1] + int(l))
    return out


def offsets_to_lengths(offsets):
    return [offsets[i + 1] - offsets[i] for i in range(len(offsets) - 1)]


class LoDTensor:
    """Padded data + recursive sequence lengths.

    `data`: np.ndarray of shape [batch, max_len, *feature] (level-1 LoD) or
    the raw dense array for lod_level=0.
    """

    def __init__(self, data, lod=None):
        self.data = np.asarray(data)
        # reference-style offsets per level
        self.lod = [list(l) for l in lod] if lod else []

    def lod_level(self):
        return len(self.lod)

    def seq_lens(self, level=0):
        if not self.lod:
            return np.full((self.data.shape[0],), self.data.shape[1], dtype=np.int32)
        return np.asarray(offsets_to_lengths(self.lod[level]), dtype=np.int32)

    def set_lod(self, lod):
        self.lod = [list(l) for l in lod]

    def shape(self):
        return self.data.shape

    def __repr__(self):
        return "LoDTensor(shape=%s, lod=%s)" % (self.data.shape, self.lod)


def _pad_ragged(flat, lens):
    """Pack consecutive groups of `flat`'s rows into a new padded axis:
    returns ([len(lens), max(lens), *flat.shape[1:]] zero-padded array,
    int32 lengths array).  This is the single primitive N-level LoD
    composition is built from — each application folds one offset level
    of lod_tensor.h's recursive index into a dense axis."""
    lens = [int(l) for l in lens]
    max_len = max(lens) if lens else 0
    out = np.zeros((len(lens), max_len) + flat.shape[1:], dtype=flat.dtype)
    ofs = 0
    for i, l in enumerate(lens):
        out[i, :l] = flat[ofs:ofs + l]
        ofs += l
    return out, np.asarray(lens, dtype=np.int32)


def create_lod_tensor(data, recursive_seq_lens=None, place=None):
    """Build a padded LoDTensor from flat data + sequence lengths, or from a
    nested list of sequences (fluid.create_lod_tensor parity,
    python/paddle/fluid/lod_tensor.py).

    LoD nesting is ARBITRARY depth, matching the reference's recursive
    offset index (lod_tensor.h:58): N levels pad to an [n0, max_1, ...,
    max_N, *feat] dense array by applying `_pad_ragged` innermost-first
    — level i's padded per-unit lengths land in `padded_lens[i]` (shape
    [n0, max_1, ..., max_i]), the mask source for sequence ops.  The
    2-level case keeps its `nested_seq_lens` alias ([docs, max_sents]
    sentence lengths)."""
    if isinstance(data, list) and data and isinstance(data[0], (list, np.ndarray)):
        seqs = [np.asarray(s) for s in data]
        lens = [len(s) for s in seqs]
        # dtype = promotion over the NON-empty sequences: an empty
        # sequence (float64 from np.asarray([])) must not promote
        # integer data, and genuine mixed dtypes still promote
        non_empty = [s for s in seqs if s.size]
        dt = np.result_type(*non_empty) if non_empty else seqs[0].dtype
        flat = np.concatenate(seqs, axis=0).astype(dt, copy=False)
        out, _ = _pad_ragged(flat, lens)
        return LoDTensor(out, [lengths_to_offsets(lens)])
    data = np.asarray(data)
    if not recursive_seq_lens:
        return LoDTensor(data)
    levels = [[int(l) for l in lev] for lev in recursive_seq_lens]
    for i in range(len(levels) - 1):
        if sum(levels[i]) != len(levels[i + 1]):
            raise ValueError(
                "level-%d lengths sum to %d but there are %d level-%d "
                "sequences" % (i, sum(levels[i]), len(levels[i + 1]), i + 1)
            )
    if sum(levels[-1]) != len(data):
        raise ValueError(
            "level-%d token lengths sum to %d but data has %d rows"
            % (len(levels) - 1, sum(levels[-1]), len(data))
        )
    # innermost first: fold token rows into sequences, then fold each
    # outer level around BOTH the data and every carried lengths array
    cur, lens_arr = _pad_ragged(data, levels[-1])
    carried = [lens_arr]  # first dim of each == #units at current level
    for lev in reversed(levels[:-1]):
        cur, lens_arr = _pad_ragged(cur, lev)
        carried = [_pad_ragged(a, lev)[0] for a in carried]
        carried.insert(0, lens_arr)
    t = LoDTensor(cur, [lengths_to_offsets(lev) for lev in levels])
    t.padded_lens = carried
    if len(levels) == 2:
        t.nested_seq_lens = carried[1]  # [docs, max_sents] back-compat
    return t
