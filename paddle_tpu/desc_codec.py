"""Binary ProgramDesc codec: Program <-> desc.proto protobuf bytes.

The compact cross-language `__model__` form (framework.proto:184 /
program_desc.h role).  JSON (`Program.to_json`) stays the human-readable
default; this module provides the lossless binary alternative plus ctypes
access to the native C++ codec (`native/desc_codec.cc`) for validation
and JSON<->binary transcode outside the Python runtime.

Save/load integration: `io.save_inference_model(..., model_format="pb")`
writes `__model__` as validated binary protobuf; `io.load_inference_model`
sniffs the format, so callers never name it.
"""

import ctypes

import numpy as np

from . import framework
from .framework import Block, Operator, Parameter, Program

__all__ = [
    "program_to_bytes",
    "program_from_bytes",
    "model_from_bytes",
    "looks_like_pb",
    "native_validate",
    "native_summary",
    "native_to_json",
    "native_max_version",
]


def _pb2():
    from .native import desc_pb2

    return desc_pb2


# ---------------------------------------------------------------------------
# attr value encoding (AttrValue oneof)
# ---------------------------------------------------------------------------
def _attr_to_pb(value, msg):
    if value is None:
        msg.none = True
    elif isinstance(value, np.ndarray):
        # raw little-endian C-order bytes; '>'-endian arrays are byteswapped
        arr = np.ascontiguousarray(value)
        if arr.dtype.byteorder == ">":
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        msg.nd.dtype = arr.dtype.name
        msg.nd.shape.extend(int(d) for d in arr.shape)
        msg.nd.data = arr.tobytes()
    elif isinstance(value, (bool, np.bool_)):  # before int: bool < int
        msg.b = bool(value)
    elif isinstance(value, (int, np.integer)):
        msg.i = int(value)
    elif isinstance(value, (float, np.floating)):
        msg.f = float(value)
    elif isinstance(value, str):
        msg.s = value
    elif isinstance(value, (list, tuple)):
        msg.list.SetInParent()  # empty list must still select the oneof
        for item in value:
            _attr_to_pb(item, msg.list.v.add())
    elif isinstance(value, dict):
        msg.dict.SetInParent()
        for k, v in value.items():
            _attr_to_pb(v, msg.dict.v[str(k)])
    else:
        raise TypeError(
            "attr value of type %s cannot be serialized to the binary "
            "__model__ format" % type(value).__name__
        )


def _attr_from_pb(msg):
    kind = msg.WhichOneof("value")
    if kind is None or kind == "none":
        return None
    if kind == "i":
        return int(msg.i)
    if kind == "f":
        return float(msg.f)
    if kind == "s":
        return msg.s
    if kind == "b":
        return bool(msg.b)
    if kind == "nd":
        arr = np.frombuffer(msg.nd.data, dtype=np.dtype(msg.nd.dtype))
        return arr.reshape(tuple(msg.nd.shape)).copy()
    if kind == "list":
        return [_attr_from_pb(v) for v in msg.list.v]
    if kind == "dict":
        return {k: _attr_from_pb(v) for k, v in msg.dict.v.items()}
    raise ValueError("unknown attr kind %r" % kind)


# ---------------------------------------------------------------------------
# program encoding
# ---------------------------------------------------------------------------
def program_to_bytes(program, feed_names=(), fetch_names=(), format_version=None):
    """Serialize a Program (+ optional feed/fetch metadata) to binary
    ProgramDesc bytes."""
    from . import io as io_mod

    pb2 = _pb2()
    prog = pb2.ProgramDesc()
    prog.format_version = (
        io_mod.PROGRAM_FORMAT_VERSION if format_version is None else int(format_version)
    )
    prog.random_seed = int(program.random_seed)
    prog.feed_names.extend(feed_names)
    prog.fetch_names.extend(fetch_names)
    for block in program.blocks:
        b = prog.blocks.add()
        b.idx = block.idx
        b.parent_idx = block.parent_idx
        for var in block.vars.values():
            v = b.vars.add()
            v.name = var.name
            if var.shape is not None:
                v.has_shape = True
                v.shape.extend(-1 if d is None else int(d) for d in var.shape)
            v.dtype = var.dtype or ""
            v.lod_level = int(var.lod_level or 0)
            v.persistable = bool(var.persistable)
            v.stop_gradient = bool(var.stop_gradient)
            v.var_type = str(var.type)
            v.is_data = bool(var.is_data)
            if isinstance(var, Parameter):
                v.is_parameter = True
                v.trainable = bool(var.trainable)
                v.optimize_attr.SetInParent()
                ser_attr = framework._serializable_optimize_attr(
                    var.optimize_attr) or {}
                for k, val in ser_attr.items():
                    _attr_to_pb(val, v.optimize_attr.v[str(k)])
        for op in block.ops:
            o = b.ops.add()
            o.type = op.type
            for slot, names in op.inputs.items():
                o.inputs[slot].v.extend(names)
            for slot, names in op.outputs.items():
                o.outputs[slot].v.extend(names)
            for k, val in op.attrs.items():
                _attr_to_pb(val, o.attrs[k])
    return prog.SerializeToString()


def model_from_bytes(data):
    """Parse binary `__model__` bytes: (Program, feed_names, fetch_names)."""
    program, msg = _parse_bytes(data)
    return program, list(msg.feed_names), list(msg.fetch_names)


def program_from_bytes(data):
    """Parse binary ProgramDesc bytes into a Program."""
    return _parse_bytes(data)[0]


def _parse_bytes(data):
    """Shared parse path.

    Raises RuntimeError on a newer-than-supported format_version (the
    version.h compat gate, same contract as the JSON loader)."""
    from . import io as io_mod

    pb2 = _pb2()
    msg = pb2.ProgramDesc()
    try:
        msg.ParseFromString(bytes(data))
    except Exception as e:
        raise ValueError("not a valid binary ProgramDesc: %s" % (e,))
    # version gate FIRST (matching desc_codec.cc's order): a future
    # format that moved/changed the blocks field must report "newer than
    # this build supports", not "empty or truncated"
    if not io_mod.is_program_version_supported(msg.format_version):
        raise RuntimeError(
            "saved model format version %s is newer than this build "
            "supports (max %s) — upgrade paddle_tpu to load it"
            % (msg.format_version, io_mod.PROGRAM_FORMAT_VERSION)
        )
    if not msg.blocks:
        # an empty/truncated file parses as an empty message — fail HERE
        # with a load-time error, not later with a bare IndexError
        raise ValueError(
            "not a valid binary ProgramDesc: no blocks (empty or truncated "
            "__model__ file)"
        )
    program = Program()
    program._seed = int(msg.random_seed)
    program.blocks = []
    for bd in msg.blocks:
        blk = Block(program, bd.idx, bd.parent_idx)
        program.blocks.append(blk)
        for vd in bd.vars:
            shape = (
                tuple(int(d) for d in vd.shape) if vd.has_shape else None
            )
            common = dict(
                shape=shape,
                dtype=vd.dtype or None,
                lod_level=int(vd.lod_level),
                persistable=vd.persistable,
                stop_gradient=vd.stop_gradient,
                type=vd.var_type,
                is_data=vd.is_data,
            )
            if vd.is_parameter:
                p = Parameter(blk, name=vd.name, **common)
                p.trainable = vd.trainable
                p.optimize_attr = {
                    k: _attr_from_pb(v) for k, v in vd.optimize_attr.v.items()
                }
                blk.vars[vd.name] = p
            else:
                blk.create_var(name=vd.name, **common)
        for v in blk.vars.values():
            if isinstance(v, Parameter):
                v.optimize_attr = framework._resolve_optimize_attr(
                    v.optimize_attr, blk)
        for od in bd.ops:
            op = Operator(blk, od.type, None, None,
                          {k: _attr_from_pb(v) for k, v in od.attrs.items()})
            op.inputs = {slot: list(nl.v) for slot, nl in od.inputs.items()}
            op.outputs = {slot: list(nl.v) for slot, nl in od.outputs.items()}
            blk.ops.append(op)
    program.current_block_idx = 0
    return program, msg


def looks_like_pb(data):
    """Format sniff for `__model__`: the JSON form starts with '{'
    (optionally after whitespace); anything else is the binary form."""
    head = bytes(data[:16]).lstrip()
    return not head.startswith(b"{")


# ---------------------------------------------------------------------------
# native codec access (desc_codec.cc via ctypes)
# ---------------------------------------------------------------------------
def _native_lib():
    from . import native

    lib = native.get_lib()
    if lib is None or not hasattr(lib, "pt_desc_validate"):
        return None
    if getattr(lib, "_desc_sigs", False) is False:
        lib.pt_desc_max_version.restype = ctypes.c_uint
        lib.pt_desc_validate.restype = ctypes.c_int
        lib.pt_desc_validate.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.pt_desc_summary.restype = ctypes.c_int
        lib.pt_desc_summary.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.POINTER(ctypes.c_long),
        ]
        lib.pt_desc_to_json.restype = ctypes.c_int
        lib.pt_desc_to_json.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_char_p, ctypes.c_int,
        ]
        lib.pt_desc_free.argtypes = [ctypes.c_char_p]
        lib._desc_sigs = True
    return lib


def native_max_version():
    """kMaxVersion of the C++ codec, or None without the native lib."""
    lib = _native_lib()
    return None if lib is None else int(lib.pt_desc_max_version())


def native_validate(data):
    """(ok, error_message) from the C++ validator; (None, reason) when the
    native library is unavailable."""
    lib = _native_lib()
    if lib is None:
        return None, "native library unavailable"
    err = ctypes.create_string_buffer(512)
    rc = lib.pt_desc_validate(bytes(data), len(data), err, len(err))
    return rc == 0, err.value.decode("utf-8", "replace")


def native_summary(data):
    """{'blocks': n, 'vars': n, 'ops': n, 'version': n} via C++, or None."""
    lib = _native_lib()
    if lib is None:
        return None
    out = (ctypes.c_long * 4)()
    if lib.pt_desc_summary(bytes(data), len(data), out) != 0:
        return None
    return {
        "blocks": int(out[0]),
        "vars": int(out[1]),
        "ops": int(out[2]),
        "version": int(out[3]),
    }


def native_to_json(data):
    """Binary -> protobuf-JSON transcode via C++ (tool-facing; the
    runtime loader uses program_from_bytes).  None when unavailable."""
    lib = _native_lib()
    if lib is None:
        return None
    out = ctypes.c_char_p()
    err = ctypes.create_string_buffer(512)
    rc = lib.pt_desc_to_json(bytes(data), len(data), ctypes.byref(out), err, len(err))
    if rc != 0:
        raise ValueError(err.value.decode("utf-8", "replace"))
    try:
        return out.value.decode("utf-8")
    finally:
        lib.pt_desc_free(out)
