"""Analytic FLOPs accounting + chip peak lookup for MFU reporting.

The reference had no MFU notion (its benchmarks report images/sec only,
benchmark/IntelOptimizedPaddle.md); on TPU the north-star metric is model
FLOPs utilization, so the bench harness walks the Program IR, sums the
matmul/conv FLOPs from compile-time shapes, and divides achieved
FLOPs/sec by the chip's peak (contrib/memory_usage_calc.py is the closest
reference analog of this kind of static program accounting).
"""

import numpy as np

__all__ = ["program_flops", "chip_peak_flops", "mfu"]


def _shape(block, name, batch_hint):
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return None
    return tuple(
        batch_hint if d in (-1, None) else int(d) for d in v.shape
    )


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


def program_flops(program, batch_hint=1):
    """Analytic forward+backward FLOPs for one execution of the program.

    Counts the matmul-class ops (where essentially all TPU FLOPs live:
    conv2d, mul/fc, matmul) from IR shapes; elementwise/norm traffic is
    bandwidth, not FLOPs, and is ignored.  Backward ops are counted as 2x
    their forward op (the standard dL/dW + dL/dX accounting), so a training
    program (which contains `*_grad` ops) lands at ~3x forward.
    Unknown (-1) dims resolve to `batch_hint`.
    """
    total = 0.0
    blk = program.global_block()
    for op in blk.ops:
        t = op.type
        grad = False
        if t.endswith("_grad"):
            t = op.attrs.get("__fwd_type__", t[: -len("_grad")])
            grad = True
        factor = 2.0 if grad else 1.0
        if t == "conv2d":
            # grad ops carry the fwd output shape via the Output@GRAD input
            out_names = (
                op.outputs.get("Output")
                or op.outputs.get("Out")
                or op.inputs.get("Output@GRAD")
                or op.inputs.get("Out@GRAD")
                or [""]
            )
            out = _shape(blk, out_names[0], batch_hint)
            flt = _shape(blk, op.inputs.get("Filter", [""])[0], batch_hint)
            if not out or not flt or len(out) != 4 or len(flt) != 4:
                continue
            n, co, ho, wo = out
            _, cin_g, kh, kw = flt
            total += factor * 2.0 * n * co * ho * wo * cin_g * kh * kw
        elif t == "conv2d_transpose":
            inp = _shape(blk, op.inputs.get("Input", [""])[0], batch_hint)
            flt = _shape(blk, op.inputs.get("Filter", [""])[0], batch_hint)
            if not inp or not flt or len(inp) != 4 or len(flt) != 4:
                continue
            n, cin, hi, wi = inp
            _, co_g, kh, kw = flt
            total += factor * 2.0 * n * cin * hi * wi * co_g * kh * kw
        elif t in ("mul", "fc", "fused_swiglu"):
            x_slot = "Input" if t == "fc" else "X"
            y_slot = ("W" if t == "fc"
                      else "GateW" if t == "fused_swiglu" else "Y")
            x = _shape(blk, op.inputs.get(x_slot, [""])[0], batch_hint)
            y = _shape(blk, op.inputs.get(y_slot, [""])[0], batch_hint)
            if not x or not y:
                continue
            ncd = int(op.attrs.get(
                "in_num_col_dims" if t == "fc" else "x_num_col_dims", 1))
            m = _prod(x[:ncd])
            k = _prod(x[ncd:])
            n2 = _prod(y[1:]) if len(y) > 1 else 1
            # SwiGLU runs TWO projections (gate + up) per op
            total += factor * 2.0 * m * k * n2 * (
                2.0 if t == "fused_swiglu" else 1.0)
        elif t == "fused_linear_xent":
            # the folded final projection: [R, H] @ [H, V]
            x = _shape(blk, op.inputs.get("X", [""])[0], batch_hint)
            w = _shape(blk, op.inputs.get("W", [""])[0], batch_hint)
            if not x or not w or len(w) != 2:
                continue
            m = _prod(x[:-1])
            k = x[-1]
            n2 = w[0] if op.attrs.get("transpose_w", False) else w[1]
            total += factor * 2.0 * m * k * n2
        elif t == "matmul":
            x = _shape(blk, op.inputs.get("X", [""])[0], batch_hint)
            y = _shape(blk, op.inputs.get("Y", [""])[0], batch_hint)
            if not x or not y:
                continue
            tx = bool(op.attrs.get("transpose_X", False))
            ty = bool(op.attrs.get("transpose_Y", False))
            m = x[-1] if tx else x[-2] if len(x) > 1 else 1
            k = x[-2] if tx else x[-1]
            n2 = y[-2] if ty else y[-1] if len(y) > 1 else 1
            batch = _prod(x[:-2]) if len(x) > 2 else 1
            total += factor * 2.0 * batch * m * k * n2
        elif t == "fused_attention":
            # QK^T + PV: 2 matmuls of [B*H, Tq, d] x [B*H, d, Tk]
            q = _shape(blk, op.inputs.get("Q", [""])[0], batch_hint)
            k = _shape(blk, op.inputs.get("K", [""])[0], batch_hint)
            if not q or not k or len(q) != 4:
                continue
            b, h, tq, d = q
            tk = k[2]
            window = int(op.attrs.get("window", 0) or 0)
            if window:  # sliding window: compute scales with the band
                tk = min(tk, window)
            total += factor * 2.0 * 2.0 * b * h * tq * tk * d
    return total


# bf16 peak FLOPs/sec per chip generation (public spec sheets)
_PEAKS = {
    "v2": 45e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "trillium": 918e12,
}


def chip_peak_flops(device=None):
    """Peak bf16 FLOPs/sec of the attached chip, or None when unknown
    (CPU fallback runs report raw throughput without an MFU claim)."""
    import os

    kind = ""
    if device is not None:
        kind = (getattr(device, "device_kind", "") or "").lower()
        if getattr(device, "platform", "") == "cpu":
            return None
    hint = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, peak in _PEAKS.items():
        if key in kind or (hint and key == hint):
            return peak
    return None


def mfu(flops_per_step, steps, seconds, device=None):
    peak = chip_peak_flops(device)
    if not peak or seconds <= 0:
        return None
    return flops_per_step * steps / seconds / peak
