"""paddle_tpu.utils"""
