"""Peak-activation-memory estimation over traced programs.

The PR 11 "logits never materialize" test walked every aval of a traced
fwd+bwd jaxpr to prove a buffer ABSENT; this module generalizes that
machinery into an analysis tool: a liveness walk over the jaxpr's
equations that estimates the peak number of simultaneously-live
intermediate bytes — the quantity an HBM budget constrains and the
rematerialization pass (transpiler.remat) optimizes.

Two deliberate properties:

* **Remat-aware.**  Call-like equations (``remat2``/``checkpoint``,
  ``pjit``, ``custom_vjp_call``, ``scan``...) recurse: a sub-jaxpr's
  internal buffers contribute a TRANSIENT spike at that equation, not
  live ranges in the outer frame.  ``jax.checkpoint`` regions therefore
  show exactly the memory the trade buys: their internals stop being
  long-lived residuals and become per-call working set.
* **Activations only.**  The top-level invars (parameters, optimizer
  state, feeds) and constants are excluded — they are resident
  regardless of scheduling; the estimator prices what the SCHEDULE
  controls.

This is an estimate, not an XLA allocator replay: fusion can elide
buffers and donation can alias them.  It is monotone under
checkpointing and ranks programs correctly, which is what budgeted
remat and the program autotuner need (docs/PERFORMANCE.md
"Optimization transpiler layer").
"""

import numpy as np

__all__ = [
    "jaxpr_peak_bytes",
    "trace_fwd_bwd",
    "estimate_peak_activation_bytes",
    "program_feed_specs",
]


def _aval_bytes(aval):
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        return n * np.dtype(dtype).itemsize
    except TypeError:
        # extended dtypes (PRNG keys): key avals carry an itemsize-less
        # dtype; 4 bytes/elem is the right order for the uint32 pairs
        return n * 4


def _sub_jaxprs(val):
    """Yield any Jaxpr / ClosedJaxpr reachable from an eqn param value."""
    import jax.core as jcore

    vals = val if isinstance(val, (list, tuple)) else [val]
    for v in vals:
        if isinstance(v, jcore.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jcore.Jaxpr):
            yield v


def jaxpr_peak_bytes(jaxpr, stream_outvars=True):
    """Liveness walk over one jaxpr: returns (peak_bytes, largest_buf).

    Live set = values defined by earlier eqns whose last textual use is
    at or after the current eqn.  invars/constvars are excluded (see
    module docstring), and with ``stream_outvars`` (the top-level
    default) the jaxpr's RESULTS are excluded too: a training trace
    returns the parameter gradients, which stream into the optimizer
    apply and are byte-identical across every remat candidate — at
    transformer-base scale they are ~240 MB that would otherwise swamp
    the ~tens-of-MB activation signal this estimator exists to rank.
    Sub-jaxprs recurse with stream_outvars=False (a call's results must
    exist when it returns).  A call-like eqn adds its sub-jaxpr's own
    peak as a transient on top of the bytes live across it."""
    import jax.core as jcore

    jaxpr = jaxpr.jaxpr if isinstance(jaxpr, jcore.ClosedJaxpr) else jaxpr
    eqns = jaxpr.eqns
    excluded = set(map(id, list(jaxpr.invars) + list(jaxpr.constvars)))
    if stream_outvars:
        excluded.update(map(id, [v for v in jaxpr.outvars
                                 if isinstance(v, jcore.Var)]))

    last_use = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if isinstance(v, jcore.Var):
                last_use[id(v)] = i
    if not stream_outvars:
        for v in jaxpr.outvars:
            if isinstance(v, jcore.Var):
                last_use[id(v)] = len(eqns)

    live = {}  # id(var) -> bytes
    peak = 0
    largest = 0
    for i, eqn in enumerate(eqns):
        inner_peak = 0
        for val in eqn.params.values():
            for sub in _sub_jaxprs(val):
                sp, sl = jaxpr_peak_bytes(sub, stream_outvars=False)
                inner_peak = max(inner_peak, sp)
                largest = max(largest, sl)
        for v in eqn.outvars:
            if isinstance(v, jcore.Var) and id(v) not in excluded:
                if last_use.get(id(v), -1) >= i:
                    b = _aval_bytes(v.aval)
                    live[id(v)] = b
                    largest = max(largest, b)
        peak = max(peak, sum(live.values()) + inner_peak)
        # free values whose last use is this eqn
        for v in eqn.invars:
            if isinstance(v, jcore.Var) and last_use.get(id(v)) == i:
                live.pop(id(v), None)
        for v in eqn.outvars:
            if isinstance(v, jcore.Var) and last_use.get(id(v), -1) <= i:
                live.pop(id(v), None)
    return peak, largest


class _SpecScope:
    """Scope stand-in for shape-level tracing: ``build_traced_function``
    insists every non-fed read exists in the scope; at program-BUILD time
    (before any startup run) only the var metadata exists.  This scope
    answers has_var from the program's var table, so the trace can run on
    ShapeDtypeStructs synthesized from the declared shapes."""

    def __init__(self, program):
        self._block = program.global_block()

    def has_var(self, name):
        return self._block._find_var_recursive(name) is not None

    def find_var(self, name):  # pragma: no cover - lowerings never peek
        return None


def program_feed_specs(program, feed_names, batch_hint=8):
    """(name -> (shape, dtype)) for the program's feed vars, resolving
    the dynamic batch dim (-1) to `batch_hint`."""
    block = program.global_block()
    specs = {}
    for name in feed_names:
        v = block._find_var_recursive(name)
        if v is None or v.shape is None:
            raise ValueError(
                "feed var %r has no declared shape; pass explicit "
                "feed_specs" % name)
        shape = tuple(batch_hint if int(d) < 0 else int(d)
                      for d in v.shape)
        specs[name] = (shape, v.dtype or "float32")
    return specs


def trace_fwd_bwd(program, feed_specs, loss_name, scope=None,
                  wrt="params"):
    """Trace the program's forward + backward into ONE ClosedJaxpr.

    The program is traced shape-level (no scope values needed): feeds
    and state become ShapeDtypeStructs from the declared var metadata,
    and ``jax.grad`` of the (summed) loss w.r.t. the trainable float
    parameters appends the backward.  Works on programs BEFORE
    ``minimize`` — which is exactly when the remat pass runs — and on
    post-minimize programs (whose explicit grad ops then simply trace
    as more forward ops).

    wrt="params" differentiates w.r.t. trainable Parameters; "none"
    traces the forward only."""
    import jax
    import jax.numpy as jnp

    from ..core.trace import build_traced_function
    from ..framework import Parameter

    spec_scope = _SpecScope(program) if scope is None else scope
    feed_names = tuple(sorted(feed_specs))
    traced = build_traced_function(
        program, 0, feed_names, [loss_name], spec_scope)
    block = program.global_block()

    feeds = {
        n: jax.ShapeDtypeStruct(tuple(shape), np.dtype(str(dtype)))
        for n, (shape, dtype) in feed_specs.items()
    }

    def struct_of(n):
        v = block._find_var_recursive(n)
        if scope is not None and hasattr(scope, "find_var"):
            arr = scope.find_var(n)
            if arr is not None and hasattr(arr, "shape"):
                return jax.ShapeDtypeStruct(
                    tuple(arr.shape), np.dtype(str(arr.dtype)))
        if v is None or v.shape is None or any(
                int(d) < 0 for d in v.shape):
            raise ValueError(
                "state var %r lacks static shape metadata" % n)
        dt = v.dtype or "float32"
        return jax.ShapeDtypeStruct(
            tuple(int(d) for d in v.shape),
            jnp.bfloat16 if dt == "bfloat16" else np.dtype(str(dt)))

    ro = {n: struct_of(n) for n in traced.ro_names}
    rw = {n: struct_of(n) for n in traced.rw_names}

    def is_trainable(n):
        v = block._find_var_recursive(n)
        return (isinstance(v, Parameter) and getattr(v, "trainable", True)
                and str(v.dtype) in ("float32", "float64", "bfloat16",
                                     "float16"))

    diff_names = (sorted(n for n in list(ro) + list(rw) if is_trainable(n))
                  if wrt == "params" else [])
    key = jax.random.PRNGKey(0)

    def fwd(diff, feeds, ro, rw, key):
        ro2 = {n: diff.get(n, v) for n, v in ro.items()}
        rw2 = {n: diff.get(n, v) for n, v in rw.items()}
        fetches, _state = traced.fn(feeds, ro2, rw2, key)
        return jnp.sum(fetches[0].astype(jnp.float32))

    if diff_names:
        def fn(feeds, ro, rw, key):
            diff = {n: (ro[n] if n in ro else rw[n]) for n in diff_names}
            loss, grads = jax.value_and_grad(fwd)(diff, feeds, ro, rw, key)
            return loss, grads
    else:
        def fn(feeds, ro, rw, key):
            return fwd({}, feeds, ro, rw, key)

    return jax.make_jaxpr(fn)(feeds, ro, rw, key)


def estimate_peak_activation_bytes(program, feed_specs, loss_name,
                                   scope=None, wrt="params"):
    """The one entry point: {'peak_bytes', 'largest_buffer_bytes',
    'n_eqns'} for the traced fwd(+bwd) of `program`.

    feed_specs: {name: (shape, dtype)} — use ``program_feed_specs`` to
    derive it from the program's data vars with a batch hint."""
    closed = trace_fwd_bwd(program, feed_specs, loss_name, scope=scope,
                           wrt=wrt)
    peak, largest = jaxpr_peak_bytes(closed)
    return {
        "peak_bytes": int(peak),
        "largest_buffer_bytes": int(largest),
        "n_eqns": len(closed.jaxpr.eqns),
    }
