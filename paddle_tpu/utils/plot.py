"""Training-curve plotting (python/paddle/utils/plot.py Ploter analog).

The reference's Ploter draws matplotlib curves inline (notebook-era book
examples).  Same API here; when matplotlib is unavailable (headless TPU
pods) it degrades to appending CSV rows so curves are still recoverable.
"""

import os


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {t: PlotData() for t in args}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")
        try:
            import matplotlib.pyplot as plt  # noqa: F401

            self._has_mpl = True
        except Exception:
            self._has_mpl = False

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert title in self.__plot_data__, (
            "title %s not initialized (Ploter(%s))" % (title, self.__args__)
        )
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        if self._has_mpl:
            import matplotlib.pyplot as plt

            titles = []
            for title in self.__args__:
                data = self.__plot_data__[title]
                if len(data.step) > 0:
                    plt.plot(data.step, data.value)
                    titles.append(title)
            plt.legend(titles, loc="upper left")
            if path is None:
                plt.show()
            else:
                plt.savefig(path)
            plt.clf()
        elif path is not None:
            # CSV fallback: one file per curve next to the requested path
            base, _ = os.path.splitext(path)
            for title in self.__args__:
                data = self.__plot_data__[title]
                with open("%s.%s.csv" % (base, title.replace(" ", "_")), "w") as f:
                    f.write("step,value\n")
                    for s, v in zip(data.step, data.value):
                        f.write("%s,%s\n" % (s, v))

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
