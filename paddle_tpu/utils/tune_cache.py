"""Shared persistence for tuning decision caches.

Both decision caches — the per-kernel block-size cache
(``ops/kernel_tuning.py``) and the per-program knob cache
(``transpiler/autotune.py``) — persist as the same JSON shape
(``{"version": 1, "entries": {key: entry}}``) under the same
discipline:

* load tolerates a missing/corrupt file with a loud warning (never an
  exception at consult time) and drops malformed entries;
* save persists SEARCHED entries only (seeded defaults are
  deterministic heuristics — nothing to remember, and a pinned CI
  cache must never gain them), MERGES with what is on disk first so
  concurrent processes sharing one path don't drop each other's
  searched keys (ours still override), and lands atomically via
  ``os.replace``.

One implementation keeps the two caches' formats and merge semantics
from drifting (the PR 11 round-2 "searched entries only" fix had to be
learned once; it must not need re-learning per cache).
"""

import json
import os

__all__ = ["load_entries", "save_entries"]


def load_entries(path, is_valid, label):
    """Entries dict from `path` (or {}): unreadable files warn and
    return empty; entries failing `is_valid(entry)` are dropped."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        import sys

        sys.stderr.write(
            "WARNING: %s %s unreadable (%r); starting empty\n"
            % (label, path, e))
        return {}
    entries = raw.get("entries", raw)
    if not isinstance(entries, dict):
        return {}
    return {k: v for k, v in entries.items()
            if isinstance(v, dict) and is_valid(v)}


def save_entries(path, entries, is_valid, label):
    """Persist the searched subset of `entries` to `path`, merged with
    the searched entries already on disk (ours override), atomically.
    Failures warn, never raise."""
    if not path:
        return
    tmp = path + ".tmp.%d" % os.getpid()
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        merged = {
            k: v for k, v in load_entries(path, is_valid, label).items()
            if v.get("searched")
        }
        merged.update({k: v for k, v in entries.items()
                       if v.get("searched")})
        with open(tmp, "w") as f:
            json.dump({"version": 1, "entries": merged},
                      f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        import sys

        sys.stderr.write(
            "WARNING: %s %s not persisted (%r)\n" % (label, path, e))
