"""Program pass infrastructure (framework/ir/pass.h + PassRegistry +
GraphPatternDetector analog).

The reference's IR layer exposes passes as registered, composable
Program-graph rewrites with a declarative subgraph matcher; XLA already
owns low-level fusion on TPU, but the *extension point* — registering a
named Program->Program rewrite and matching op patterns declaratively —
is framework surface users build on (custom quantization, fusion, layout
rewrites).  This module provides:

- ``Pass`` / ``register_pass`` / ``get_pass`` / ``apply_pass`` — the
  PassRegistry contract (ir/pass.h:Pass::Apply, PassRegistry).
- ``OpPattern.match`` — a GraphPatternDetector-lite: matches a linear
  producer chain of op types through the program's def-use graph and
  hands each occurrence to a rewrite callback.
- Built-in registrations for the existing rewrites (bn fold, train-op
  drop, memory plan, bf16 AMP) so ``apply_pass(prog, name)`` works the
  way ``PassBuilder`` exposes passes to Python (pybind.cc:664).
"""

__all__ = [
    "Pass",
    "register_pass",
    "get_pass",
    "list_passes",
    "apply_pass",
    "OpPattern",
]

_PASSES = {}


class Pass:
    """Base class: subclasses implement apply(program, scope=None)."""

    name = None

    def apply(self, program, scope=None):
        raise NotImplementedError

    def __call__(self, program, scope=None):
        return self.apply(program, scope=scope)


def register_pass(name):
    """Decorator registering a Pass subclass or a function
    program -> program under `name` (REGISTER_PASS analog)."""

    def deco(obj):
        if isinstance(obj, type) and issubclass(obj, Pass):
            inst = obj()
            inst.name = name
            _PASSES[name] = inst
        else:
            p = Pass()
            p.name = name
            p.apply = lambda program, scope=None, _f=obj: _f(program, scope)
            _PASSES[name] = p
        return obj

    return deco


def get_pass(name):
    if name not in _PASSES:
        raise KeyError(
            "no pass '%s' registered (known: %s)" % (name, sorted(_PASSES))
        )
    return _PASSES[name]


def list_passes():
    return sorted(_PASSES)


def apply_pass(program, name, scope=None):
    """Apply one registered pass; returns the (possibly same) program.

    Under ``FLAGS_check_program`` the result is statically re-verified
    (analysis.verify_after_pass): verified-in => verified-out becomes a
    structural property of every registry pass, and a pass emitting an
    ill-formed program fails HERE with the pass and offending op named
    instead of at trace time.  Flag off = one flag read, no other cost.
    """
    out = get_pass(name).apply(program, scope=scope)
    out = out if out is not None else program
    from ..flags import get_flag

    if get_flag("check_program"):
        from ..analysis import verify_after_pass

        verify_after_pass(out, name, scope=scope)
    return out


class OpPattern:
    """GraphPatternDetector-lite: a linear chain of op types connected by
    def-use edges.

        n = OpPattern(["mul", "elementwise_add", "relu"]).rewrite(
                block, lambda ops: fuse(ops))

    The matcher walks the block once, following single-consumer def-use
    links; `rewrite` calls the callback with each matched op list (in
    chain order) and lets it mutate the block (return True to count a
    rewrite)."""

    def __init__(self, op_types):
        self.op_types = list(op_types)

    def _consumer_map(self, block):
        from ..analysis.graph import consumer_map

        return consumer_map(block)

    def match(self, block):
        """Yield lists of Operators matching the chain."""
        consumers = self._consumer_map(block)
        for i, op in enumerate(block.ops):
            if op.type != self.op_types[0]:
                continue
            chain = [op]
            ok = True
            cur = op
            for want in self.op_types[1:]:
                outs = cur.output_arg_names()
                nxt = None
                for name in outs:
                    cs = consumers.get(name, [])
                    # single-consumer edge keeps the rewrite sound (the
                    # intermediate value must not be used elsewhere)
                    if len(cs) == 1 and block.ops[cs[0]].type == want:
                        nxt = block.ops[cs[0]]
                        break
                if nxt is None:
                    ok = False
                    break
                chain.append(nxt)
                cur = nxt
            if ok:
                yield chain

    def rewrite(self, block, fn):
        """Apply fn(list of ops) -> bool to every match; returns count of
        rewrites.  Matches are re-scanned after each mutation, but a chain
        already handed to fn is never re-offered — so attr-tagging
        rewrites that leave the match intact still terminate."""
        count = 0
        seen = set()
        changed = True
        while changed:
            changed = False
            for chain in self.match(block):
                key = tuple(id(op) for op in chain)
                if key in seen:
                    continue
                seen.add(key)
                if fn(chain):
                    count += 1
                    changed = True
                    break  # ops list mutated: re-scan
        return count


# ---------------------------------------------------------------------------
# built-in pass registrations (the PassBuilder default pipeline analog)
# ---------------------------------------------------------------------------
@register_pass("conv_bn_fuse_pass")
def _conv_bn_fuse(program, scope):
    """Back-compat alias of bn_fold_pass (the fold long ago outgrew
    conv: it now also takes fc/mul producers and scale chains) — one
    implementation, two names, so a pipeline listing both cannot
    diverge."""
    return _bn_fold(program, scope)


@register_pass("is_test_pass")
def _is_test(program, scope):
    from .inference_transpiler import InferenceTranspiler

    t = InferenceTranspiler()
    t._drop_train_ops(program)
    return program


@register_pass("bn_fold_pass")
def _bn_fold(program, scope):
    """BN/scale-chain fold into conv2d / depthwise_conv2d / fc / mul
    weights (the generalized inference-transpiler sub-pass; a trailing
    relu is untouched and stays eligible for the conv fuse passes).
    Parity contract: rtol 1e-5 vs the unfused program, >= 1 op dropped
    per folded BN."""
    from .inference_transpiler import InferenceTranspiler

    if scope is None:
        raise ValueError(
            "bn_fold_pass folds BN statistics into producer weights and "
            "needs the scope holding them: apply_pass(prog, "
            "'bn_fold_pass', scope=...)")
    InferenceTranspiler()._fold_batch_norm(program, scope)
    return program


@register_pass("train_prune_pass")
def _train_prune(program, scope):
    """Drop train-only ops (dropout -> is_test form) and, when the
    program carries ``_protected_fetch_names``, slice away everything
    below the inference cut — label slots, loss heads, metric ops.
    Parity contract: protected fetches are value-identical."""
    from .inference_transpiler import InferenceTranspiler

    t = InferenceTranspiler()
    t._drop_train_ops(program)
    t._prune_to_fetches(program)
    return program


@register_pass("weight_int8_pass")
def _weight_int8(program, scope):
    """Weight-only int8 stamping for ANY program (the serving engine's
    quantize_weights_int8 generalized into a registry pass): persistable
    mul/matmul/conv/embedding weights become int8+scale pairs
    dequantized at compute time, f32 originals dropped when dead.
    Parity contract: the documented post-training-quant tolerance
    (tests/test_quant_int8.py)."""
    from ..contrib.quantize import quantize_weights_int8

    if scope is None:
        raise ValueError(
            "weight_int8_pass rewrites weights in the scope: "
            "apply_pass(prog, 'weight_int8_pass', scope=...)")
    quantize_weights_int8(program, scope=scope)
    return program


@register_pass("memory_optimize_pass")
def _memory_optimize(program, scope):
    from .memory_optimization_transpiler import memory_optimize

    memory_optimize(program)
    return program


@register_pass("bf16_amp_pass")
def _bf16_amp(program, scope):
    from ..contrib.mixed_precision import rewrite_bf16

    rewrite_bf16(program)
    return program


@register_pass("nhwc_layout_pass")
def _nhwc_layout(program, scope):
    from .layout_transpiler import rewrite_nhwc

    rewrite_nhwc(program)
    return program


@register_pass("graph_viz_pass")
def _graph_viz(program, scope):
    """ir/graph_viz_pass.cc analog: dump the program's def-use graph as
    graphviz dot.  Output path via program._graph_viz_path (the
    BuildStrategy.debug_graphviz_path plumbing) or ./graph.dot."""
    from ..debugger import draw_block_graphviz

    path = getattr(program, "_graph_viz_path", "") or "./graph.dot"
    draw_block_graphviz(program.global_block(), path=path)
    return program


@register_pass("fuse_relu_into_conv_pass")
class FuseReluIntoConv(Pass):
    """Example fusion built on OpPattern: conv2d followed by a
    single-consumer relu becomes conv2d(act=relu) via the fused-activation
    attr the lowering honors (fuse_elewise_add_act_pass spirit — XLA would
    fuse these anyway; the pass exists as the extension-point demo and to
    shrink the traced op count)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            conv, relu = chain
            out_name = relu.outputs["Out"][0]
            conv.outputs["Output"] = [out_name]
            conv.attrs["fuse_relu"] = True
            block.ops.remove(relu)
            program._bump_version()
            return True

        OpPattern(["conv2d", "relu"]).rewrite(block, fuse)
        return program


@register_pass("attention_fuse_pass")
class AttentionFusePass(Pass):
    """Scaled-dot-product attention fusion (the attention_lstm_fuse_pass
    family analog, aimed at the one pattern XLA cannot collapse into an
    O(T)-memory kernel by itself):

        matmul(Q, K, transpose_Y, alpha)
          [-> elementwise_add(rank-1-in-Tk bias)]
          -> softmax [-> dropout(is_test)]
          -> matmul(weights, V)

    becomes ONE fused_attention op — flash kernel under FLAGS_use_pallas,
    fused XLA otherwise.  Conservative conditions: single-consumer chain
    (the matcher guarantees it), Q rank-4 [B, H, Tq, Dh], bias with key
    axis only (shape [..., 1, Tk]), softmax over the default last axis,
    inference-mode dropout only.
    """

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            m1 = chain[0]
            m2 = chain[-1]
            mid = chain[1:-1]
            add = next((o for o in mid if o.type == "elementwise_add"), None)
            sm = next((o for o in mid if o.type == "softmax"), None)
            drop = next((o for o in mid if o.type == "dropout"), None)
            if sm is None:
                return False
            if not m1.attrs.get("transpose_Y", False) or m1.attrs.get(
                "transpose_X", False
            ):
                return False
            if m2.attrs.get("transpose_X") or m2.attrs.get("transpose_Y"):
                return False
            # the probabilities must be matmul2's LHS (weights @ V)
            prob_name = (drop or sm).outputs["Out"][0]
            if m2.inputs.get("X", [None])[0] != prob_name:
                return False
            if sm.attrs.get("axis", -1) not in (-1,):
                return False
            if drop is not None and not drop.attrs.get("is_test", False):
                return False
            # downgrade_in_infer scales the probabilities by (1-p) at
            # inference — fold that into a scale op after the fused kernel
            post_scale = 1.0
            if drop is not None and drop.attrs.get(
                "dropout_implementation", "downgrade_in_infer"
            ) == "downgrade_in_infer":
                post_scale = 1.0 - float(drop.attrs.get("dropout_prob", 0.0))
            qvar = block._find_var_recursive(m1.inputs["X"][0])
            kvar = block._find_var_recursive(m1.inputs["Y"][0])
            vvar = block._find_var_recursive(m2.inputs["Y"][0])
            if any(
                v is None or v.shape is None or len(v.shape) != 4
                for v in (qvar, kvar, vvar)
            ):
                return False

            def _dim(v, i):
                return int(v.shape[i])

            # kernel contract: K/V share Q's batch/head/feature dims and
            # each other's Tk (fused_attention reshapes K/V with Q's b, h,
            # d — MQA-style broadcastable K/V must stay on the matmul path)
            if (
                _dim(kvar, 0) != _dim(qvar, 0)
                or _dim(vvar, 0) != _dim(qvar, 0)
                or _dim(kvar, 1) != _dim(qvar, 1)
                or _dim(vvar, 1) != _dim(qvar, 1)
                or _dim(kvar, 3) != _dim(qvar, 3)
                or _dim(vvar, 3) != _dim(qvar, 3)
                or (_dim(kvar, 2) != -1 and _dim(vvar, 2) != -1
                    and _dim(kvar, 2) != _dim(vvar, 2))
            ):
                return False
            inputs = {
                "Q": m1.inputs["X"],
                "K": m1.inputs["Y"],
                "V": m2.inputs["Y"],
            }
            if add is not None:
                # the bias is whichever add operand is NOT the QK^T product
                prod_name = m1.outputs["Out"][0]
                add_ins = add.inputs.get("X", []) + add.inputs.get("Y", [])
                others = [n for n in add_ins if n != prod_name]
                if prod_name not in add_ins or len(others) != 1:
                    return False
                bname = others[0]
                bvar = block._find_var_recursive(bname)
                # fused Bias contract: reshapeable to [B, Tk] — require
                # [B, 1, 1, Tk] with a per-example batch (dynamic or equal
                # to Q's); broadcast ([1,1,1,Tk]) or per-head biases would
                # crash the fused reshape, leave those graphs alone
                if (
                    bvar is None
                    or bvar.shape is None
                    or len(bvar.shape) != 4
                    or int(bvar.shape[1]) != 1
                    or int(bvar.shape[2]) != 1
                    or (int(bvar.shape[0]) not in (-1,)
                        and int(bvar.shape[0]) != int(qvar.shape[0]))
                    or (int(bvar.shape[3]) != -1 and _dim(kvar, 2) != -1
                        and int(bvar.shape[3]) != _dim(kvar, 2))
                ):
                    return False
                inputs["Bias"] = [bname]
            import paddle_tpu.framework as _fw

            fused = _fw.Operator(
                block,
                "fused_attention",
                None,
                None,
                {
                    "causal": False,
                    "scale": float(m1.attrs.get("alpha", 1.0)),
                },
            )
            fused.inputs = inputs
            out_name = m2.outputs["Out"][0]
            # insert where the SECOND matmul sat: every fused input (incl.
            # a V/Bias produced between the two matmuls) is defined there;
            # the executor runs block.ops strictly in list order
            idx = block.ops.index(m2) - (len(chain) - 1)
            new_ops = [fused]
            if post_scale != 1.0:
                raw = out_name + "@ATTN_RAW"
                ov = block._find_var_recursive(out_name)
                block.create_var(
                    name=raw,
                    shape=list(ov.shape) if ov is not None and ov.shape else None,
                    dtype=ov.dtype if ov is not None else "float32",
                )
                fused.outputs = {"Out": [raw]}
                scale_op = _fw.Operator(
                    block, "scale", None, None,
                    {"scale": post_scale, "bias": 0.0,
                     "bias_after_scale": True},
                )
                scale_op.inputs = {"X": [raw]}
                scale_op.outputs = {"Out": [out_name]}
                new_ops.append(scale_op)
            else:
                fused.outputs = {"Out": [out_name]}
            for op in chain:
                block.ops.remove(op)
            for j, op in enumerate(new_ops):
                block.ops.insert(idx + j, op)
            program._bump_version()
            return True

        n = 0
        for pat in (
            ["matmul", "elementwise_add", "softmax", "dropout", "matmul"],
            ["matmul", "elementwise_add", "softmax", "matmul"],
            ["matmul", "softmax", "dropout", "matmul"],
            ["matmul", "softmax", "matmul"],
        ):
            n += OpPattern(pat).rewrite(block, fuse)
        program._attention_fused_count = n
        return program
