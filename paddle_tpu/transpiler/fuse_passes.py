"""Fusion-pass suite on the OpPattern detector — the parity sweep over the
reference's ir/ fuse passes (framework/ir/fc_fuse_pass.cc,
fuse_elewise_add_act_pass.cc, conv_elementwise_add*_mkldnn_fuse_pass,
seqconv_eltadd_relu_fuse_pass.cc, fc_gru_fuse_pass.cc,
fc_lstm_fuse_pass.cc, embedding_fc_lstm_fuse_pass.cc).

Each pass is an op-level Program rewrite into a fused op whose lowering
already exists — changing WHICH HLO is emitted (fewer, bigger ops with
epilogues attached to the matmul/conv), the same lever the reference's
inference-perf story pulls.  All rewrites are conservative: they require
the exact single-consumer chains the OpPattern matcher guarantees plus
local shape/attr conditions, and leave anything else untouched.  Every
fused target op is differentiable through the generic vjp machinery, so
the fc/elewise passes are train-safe (BuildStrategy.fuse_elewise_add_act_ops).
"""

import paddle_tpu.framework as _fw

from .pass_registry import OpPattern, Pass, register_pass

_ACTS = ("relu", "tanh", "sigmoid")
# fc epilogue activations: the fc lowering's matmul-epilogue kernel set
# (pallas_kernels._MM_ACTS).  gelu fuses only in its exact-erf default
# form and swish only at beta=1 — _act_fusable checks the attrs.
_FC_ACTS = ("relu", "tanh", "sigmoid", "gelu", "swish")


def _act_fusable(act_op):
    """True when the activation op's attrs match the fused epilogue's
    fixed form (exact gelu, beta-1 swish; the plain acts always do)."""
    if act_op.type == "gelu":
        return not act_op.attrs.get("approximate", False)
    if act_op.type == "swish":
        return float(act_op.attrs.get("beta", 1.0)) == 1.0
    return True


def _mk_op(block, type_, inputs, outputs, attrs):
    op = _fw.Operator(block, type_, None, None, dict(attrs))
    op.inputs = inputs
    op.outputs = outputs
    return op


def _chain_safe(program, chain):
    """A fuse rewrite deletes every intermediate output of the chain; names
    the caller wants fetchable (program._protected_fetch_names, set by the
    ParallelExecutor / predictor before applying passes) must survive."""
    protected = getattr(program, "_protected_fetch_names", None)
    if not protected:
        return True
    for op in chain[:-1]:
        if any(n in protected for n in op.output_arg_names()):
            return False
    return True


def _replace_chain(block, program, chain, new_ops):
    """Swap a matched chain for new ops at the position of the LAST chain
    op (all producers of the fused inputs are defined by then)."""
    idx = block.ops.index(chain[-1]) - (len(chain) - 1)
    for op in chain:
        block.ops.remove(op)
    for j, op in enumerate(new_ops):
        block.ops.insert(idx + j, op)
    program._bump_version()


def _bias_of_add(block, add, producer_out):
    """The add operand that is NOT `producer_out`, or None."""
    add_ins = add.inputs.get("X", []) + add.inputs.get("Y", [])
    others = [n for n in add_ins if n != producer_out]
    if producer_out not in add_ins or len(others) != 1:
        return None
    return others[0]


def _is_bias_vector(block, name, want, channel_axis_from_end):
    """True when the var is a length-`want` vector laid out so broadcasting
    against the producer's output applies it along the intended axis: all
    dims 1 except the one `channel_axis_from_end` positions from the end
    (rank may be anything <= that+1).  A numel-only check would accept
    e.g. a [1,1,H,W] positional bias as a per-channel one."""
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return False
    dims = [int(d) for d in v.shape]
    if any(d < 0 for d in dims):
        return False
    n = 1
    for d in dims:
        n *= d
    if n != int(want):
        return False
    # locate the channel axis from the right; a bare [C] vector counts
    # only for k == 0 (it right-broadcasts onto the last axis)
    k = channel_axis_from_end
    if len(dims) <= k:
        return k == 0 and len(dims) == 1
    return dims[len(dims) - 1 - k] == int(want) and all(
        d == 1 for i, d in enumerate(dims) if i != len(dims) - 1 - k)


@register_pass("fc_fuse_pass")
class FcFusePass(Pass):
    """mul + elementwise_add [+ relu/tanh/sigmoid] -> fc
    (ir/fc_fuse_pass.cc)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            mul, add = chain[0], chain[1]
            act = chain[2].type if len(chain) == 3 else ""
            if len(chain) == 3 and not _act_fusable(chain[2]):
                return False
            if int(mul.attrs.get("y_num_col_dims", 1)) != 1:
                return False
            w = block._find_var_recursive(mul.inputs["Y"][0])
            if w is None or w.shape is None or len(w.shape) != 2:
                return False  # fc lowering matmuls Y as-is (no flattening)
            size = int(w.shape[-1])
            bname = _bias_of_add(block, add, mul.outputs["Out"][0])
            if bname is None or not _is_bias_vector(block, bname, size, 0):
                return False
            if not _chain_safe(program, chain):
                return False
            fc = _mk_op(
                block, "fc",
                {"Input": mul.inputs["X"], "W": mul.inputs["Y"],
                 "Bias": [bname]},
                {"Out": [chain[-1].outputs["Out"][0]]},
                {"in_num_col_dims": int(mul.attrs.get("x_num_col_dims", 1)),
                 "activation_type": act},
            )
            _replace_chain(block, program, chain, [fc])
            return True

        n = 0
        for pat in ([["mul", "elementwise_add", a] for a in _FC_ACTS]
                    + [["mul", "elementwise_add"]]):
            n += OpPattern(pat).rewrite(block, fuse)
        program._fc_fused_count = n
        return program


@register_pass("fuse_elewise_add_act_pass")
class FuseElewiseAddActPass(Pass):
    """elementwise_add + activation -> fused_elemwise_activation
    (ir/fuse_elewise_add_act_pass.cc; Unary(Binary(x, y)) convention)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            add, act = chain
            if int(add.attrs.get("axis", -1)) != -1:
                return False  # the fused lowering applies plain + only
            if not _chain_safe(program, chain):
                return False
            fused = _mk_op(
                block, "fused_elemwise_activation",
                {"X": [add.inputs["X"][0]], "Y": [add.inputs["Y"][0]]},
                {"Out": [act.outputs["Out"][0]]},
                {"functor_list": [act.type, "elementwise_add"]},
            )
            _replace_chain(block, program, chain, [fused])
            return True

        n = 0
        for a in _ACTS:
            n += OpPattern(["elementwise_add", a]).rewrite(block, fuse)
        program._elewise_act_fused_count = n
        return program


@register_pass("conv_eltadd_relu_fuse_pass")
class ConvEltaddReluFusePass(Pass):
    """conv2d + elementwise_add(per-channel bias) [+ relu] -> conv2d with
    Bias input and fuse_relu epilogue (conv_bias/conv_relu mkldnn passes
    + fuse_relu_into_conv_pass combined)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            conv, add = chain[0], chain[1]
            relu = chain[2] if len(chain) == 3 else None
            if conv.inputs.get("Bias"):
                return False  # already biased
            f = block._find_var_recursive(conv.inputs["Filter"][0])
            if f is None or f.shape is None:
                return False
            cout = int(f.shape[0])
            bname = _bias_of_add(block, add, conv.outputs["Output"][0])
            if bname is None:
                return False
            # NCHW channel bias arrives either as [*,C,1,1] under plain
            # broadcasting, or as a bare [C] with fluid's axis=1 add
            axis = int(add.attrs.get("axis", -1))
            if axis == 1:
                bv = block._find_var_recursive(bname)
                if (bv is None or bv.shape is None
                        or [int(d) for d in bv.shape] != [cout]):
                    return False
            elif not _is_bias_vector(block, bname, cout, 2):
                return False
            if not _chain_safe(program, chain):
                return False
            conv.inputs["Bias"] = [bname]
            conv.outputs["Output"] = [chain[-1].outputs["Out"][0]]
            if relu is not None:
                conv.attrs["fuse_relu"] = True
            # reposition the conv to the chain tail: its new Bias input may
            # be produced between the conv and the add (e.g. a reshape)
            _replace_chain(block, program, chain, [conv])
            return True

        n = 0
        for pat in (["conv2d", "elementwise_add", "relu"],
                    ["conv2d", "elementwise_add"]):
            n += OpPattern(pat).rewrite(block, fuse)
        program._conv_eltadd_fused_count = n
        return program


@register_pass("seqconv_eltadd_relu_fuse_pass")
class SeqconvEltaddReluFusePass(Pass):
    """sequence_conv + elementwise_add + relu ->
    fusion_seqconv_eltadd_relu (ir/seqconv_eltadd_relu_fuse_pass.cc)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            sc, add, relu = chain
            f = block._find_var_recursive(sc.inputs["Filter"][0])
            if f is None or f.shape is None:
                return False
            nfilt = int(f.shape[-1])
            bname = _bias_of_add(block, add, sc.outputs["Out"][0])
            if bname is None or not _is_bias_vector(block, bname, nfilt, 0):
                return False
            if not _chain_safe(program, chain):
                return False
            inputs = {"X": sc.inputs["X"], "Filter": sc.inputs["Filter"],
                      "Bias": [bname]}
            if sc.inputs.get("SeqLen"):
                inputs["SeqLen"] = sc.inputs["SeqLen"]
            fused = _mk_op(
                block, "fusion_seqconv_eltadd_relu", inputs,
                {"Out": [relu.outputs["Out"][0]]}, sc.attrs,
            )
            _replace_chain(block, program, chain, [fused])
            return True

        n = OpPattern(["sequence_conv", "elementwise_add", "relu"]).rewrite(
            block, fuse)
        program._seqconv_fused_count = n
        return program


def _fuse_fc_into_recurrent(program, rec_types, fused_type):
    """Shared body of fc_gru_fuse_pass / fc_lstm_fuse_pass: an fc (or bare
    mul) producing the recurrent op's Input becomes the WeightX/BiasX
    in-op projection."""
    block = program.global_block()

    def fuse(chain):
        proj, rec = chain
        if rec.inputs.get("WeightX"):
            return False
        if proj.outputs["Out"][0] != rec.inputs["Input"][0]:
            return False
        if not _chain_safe(program, chain):
            return False
        x_in = proj.inputs["Input" if proj.type == "fc" else "X"][0]
        xv = block._find_var_recursive(x_in)
        if xv is None or xv.shape is None or len(xv.shape) != 3:
            return False  # in-op projection is [B, T, D] @ [D, kH]
        if proj.type == "fc":
            if proj.attrs.get("activation_type"):
                return False
            if int(proj.attrs.get("in_num_col_dims", 1)) != 2:
                return False
            rec.inputs["WeightX"] = proj.inputs["W"]
            if proj.inputs.get("Bias"):
                rec.inputs["BiasX"] = proj.inputs["Bias"]
        else:  # bare mul
            if int(proj.attrs.get("x_num_col_dims", 1)) != 2:
                return False
            rec.inputs["WeightX"] = proj.inputs["Y"]
        rec.inputs["Input"] = [x_in]
        rec.type = fused_type
        block.ops.remove(proj)
        program._bump_version()
        return True

    n = 0
    for rec_type in rec_types:
        for head in ("fc", "mul"):
            n += OpPattern([head, rec_type]).rewrite(block, fuse)
    return n


@register_pass("fc_gru_fuse_pass")
def _fc_gru_fuse(program, scope):
    """fc/mul + gru -> fusion_gru (ir/fc_gru_fuse_pass.cc)."""
    program._fc_gru_fused_count = _fuse_fc_into_recurrent(
        program, ("gru", "padded_gru"), "fusion_gru")
    return program


@register_pass("fc_lstm_fuse_pass")
def _fc_lstm_fuse(program, scope):
    """fc/mul + lstm -> fusion_lstm (ir/fc_lstm_fuse_pass.cc)."""
    program._fc_lstm_fused_count = _fuse_fc_into_recurrent(
        program, ("lstm", "padded_lstm"), "fusion_lstm")
    return program


@register_pass("seqexpand_concat_fc_fuse_pass")
class SeqexpandConcatFcFusePass(Pass):
    """sequence_expand(s) + concat + fc/mul -> fusion_seqexpand_concat_fc
    (ir/seq_concat_fc_fuse_pass.cc role on the padded representation).

    Run AFTER fc_fuse_pass: mul+bias+act chains have already collapsed to
    fc, so matching fc (or a bare mul) here covers the general pattern.
    The concat's first input is the [B, T, D] sequence; every further
    input must be a single-consumer sequence_expand of a [B, Di] vector.
    """

    def apply(self, program, scope=None):
        block = program.global_block()
        n = 0
        changed = True
        while changed:
            changed = False
            from ..analysis.graph import consumer_ops, producer_ops

            producers, consumers = producer_ops(block), consumer_ops(block)
            for cat in list(block.ops):
                if cat.type != "concat":
                    continue
                if int(cat.attrs.get("axis", 0)) not in (2, -1):
                    continue
                xs = cat.inputs.get("X", [])
                if len(xs) < 2:
                    continue
                sv = block._find_var_recursive(xs[0])
                if sv is None or sv.shape is None or len(sv.shape) != 3:
                    continue
                expands = []
                for name in xs[1:]:
                    p = producers.get(name)
                    xv = (
                        block._find_var_recursive(p.inputs["X"][0])
                        if p is not None and p.type == "sequence_expand"
                        else None
                    )
                    if (
                        p is None or p.type != "sequence_expand"
                        or xv is None or xv.shape is None
                        or len(xv.shape) != 2
                        or len(consumers.get(name, [])) != 1
                    ):
                        expands = None
                        break
                    expands.append(p)
                if not expands:
                    continue
                cat_out = cat.outputs["Out"][0]
                cons = consumers.get(cat_out, [])
                if len(cons) != 1:
                    continue
                proj = cons[0]
                if proj.type == "fc":
                    if int(proj.attrs.get("in_num_col_dims", 1)) != 2:
                        continue
                    if proj.inputs.get("Input", [None])[0] != cat_out:
                        continue
                    weight = proj.inputs["W"]
                    bias = proj.inputs.get("Bias")
                    act = proj.attrs.get("activation_type") or "identity"
                elif proj.type == "mul":
                    if int(proj.attrs.get("x_num_col_dims", 1)) != 2:
                        continue
                    if int(proj.attrs.get("y_num_col_dims", 1)) != 1:
                        continue
                    if proj.inputs.get("X", [None])[0] != cat_out:
                        continue
                    wv = block._find_var_recursive(proj.inputs["Y"][0])
                    if wv is None or wv.shape is None or len(wv.shape) != 2:
                        continue  # fused lowering matmuls FCWeight as-is
                    weight = proj.inputs["Y"]
                    bias = None
                    act = "identity"
                else:
                    continue
                if act not in ("identity", "relu", "tanh", "sigmoid"):
                    continue
                chain = expands + [cat, proj]
                if not _chain_safe(program, chain):
                    continue
                inputs = {
                    "X": [xs[0]] + [e.inputs["X"][0] for e in expands],
                    "FCWeight": weight,
                }
                if bias:
                    inputs["FCBias"] = bias
                fused = _mk_op(
                    block, "fusion_seqexpand_concat_fc", inputs,
                    {"Out": [proj.outputs["Out"][0]]},
                    {"fc_activation": act},
                )
                # insert at the projection's position (all fused inputs
                # are defined by then); the chain need not be contiguous
                block.ops.insert(block.ops.index(proj), fused)
                for op in chain:
                    block.ops.remove(op)
                program._bump_version()
                n += 1
                changed = True
                break
        program._seqexpand_concat_fc_fused_count = n
        return program


@register_pass("embedding_fc_lstm_fuse_pass")
class EmbeddingFcLstmFusePass(Pass):
    """lookup_table + fc/mul + lstm -> fused_embedding_fc_lstm
    (ir/embedding_fc_lstm_fuse_pass.cc)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            lt, proj, lstm = chain
            if proj.outputs["Out"][0] != lstm.inputs["Input"][0]:
                return False
            if lt.attrs.get("padding_idx", -1) not in (-1, None):
                return False
            # the embedding output must be the projection's DATA side —
            # a lookup feeding the weight operand is a different graph
            emb_out = lt.outputs["Out"][0]
            data_slot = "Input" if proj.type == "fc" else "X"
            if proj.inputs.get(data_slot, [None])[0] != emb_out:
                return False
            inputs = {
                "Ids": lt.inputs["Ids"],
                "Embeddings": lt.inputs["W"],
                "WeightH": lstm.inputs["Weight"],
            }
            if proj.type == "fc":
                if proj.attrs.get("activation_type"):
                    return False
                if int(proj.attrs.get("in_num_col_dims", 1)) != 2:
                    return False
                inputs["WeightX"] = proj.inputs["W"]
                if proj.inputs.get("Bias"):
                    inputs["BiasX"] = proj.inputs["Bias"]
            else:
                if int(proj.attrs.get("x_num_col_dims", 1)) != 2:
                    return False
                inputs["WeightX"] = proj.inputs["Y"]
            if not _chain_safe(program, chain):
                return False
            for slot in ("Bias", "SeqLen", "H0", "C0"):
                if lstm.inputs.get(slot):
                    inputs[slot] = lstm.inputs[slot]
            fused = _mk_op(
                block, "fused_embedding_fc_lstm", inputs,
                dict(lstm.outputs), lstm.attrs,
            )
            _replace_chain(block, program, chain, [fused])
            return True

        n = 0
        for rec in ("lstm", "padded_lstm"):
            for head in ("fc", "mul"):
                n += OpPattern(["lookup_table", head, rec]).rewrite(
                    block, fuse)
        program._emb_fc_lstm_fused_count = n
        return program


@register_pass("smooth_label_xent_fuse_pass")
class SmoothLabelXentFusePass(Pass):
    """one_hot -> label_smooth -> softmax_with_cross_entropy(soft_label)
    => ONE smooth_label_xent op reading the raw int labels.

    The reference training-loss idiom (dist_transformer.py builds exactly
    this chain) materializes three [N, V] float arrays — one-hot labels,
    smoothed labels, log-softmax — purely to compute a closed-form
    quantity; on TPU that is pure HBM traffic.  Conservative conditions:
    uniform prior only (no PriorDist), soft_label=True, no ignore_index,
    the xent's Softmax output unused, depth == one_hot attr, and the
    usual single-consumer chain + protected-fetch safety.  Train-safe:
    smooth_label_xent differentiates through the generic vjp."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            oh, smooth, xent = chain
            if not bool(xent.attrs.get("soft_label", False)):
                return False
            if int(xent.attrs.get("ignore_index", -100)) >= 0:
                return False
            if smooth.inputs.get("PriorDist"):
                return False  # closed form assumes the uniform prior
            if not _chain_safe(program, chain):
                return False
            softmax_out = xent.outputs.get("Softmax", [None])[0]
            if softmax_out:
                protected = getattr(program, "_protected_fetch_names", ())
                if softmax_out in protected or _consumers_all_blocks(
                        program, softmax_out, exclude=(xent,)):
                    return False
            # OpPattern's single-consumer scan only covers the global
            # block: a sub-block reading an intermediate would be left
            # dangling by the rewrite
            oh_out = oh.outputs["Out"][0]
            sm_out = smooth.outputs["Out"][0]
            if _consumers_all_blocks(program, oh_out,
                                     exclude=(oh, smooth)):
                return False
            if _consumers_all_blocks(program, sm_out,
                                     exclude=(smooth, xent)):
                return False
            label_name = oh.inputs["X"][0]
            logits_name = xent.inputs["Logits"][0]
            lv = block._find_var_recursive(logits_name)
            # default CLOSED on missing shape info, like every pass here:
            # the unfused chain fails loudly on a depth mismatch; the
            # fused form would compute a plausible wrong loss silently
            if lv is None or lv.shape is None:
                return False
            if int(lv.shape[-1]) != int(oh.attrs.get("depth", -1)):
                return False
            fused = _mk_op(
                block,
                "smooth_label_xent",
                {"Logits": [logits_name], "Label": [label_name]},
                {"Loss": list(xent.outputs["Loss"])},
                {"epsilon": float(smooth.attrs.get("epsilon", 0.0))},
            )
            _replace_chain(block, program, chain, [fused])
            return True

        n = OpPattern(
            ["one_hot", "label_smooth", "softmax_with_cross_entropy"]
        ).rewrite(block, fuse)
        program._smooth_xent_fused_count = n
        return program


def _consumers_all_blocks(program, name, exclude=()):
    """Every op in ANY block reading `name` (sub-block reads count —
    the shared safety scan of the xent/epilogue passes)."""
    return [
        op
        for blk in program.blocks
        for op in blk.ops
        if op not in exclude and name in op.input_arg_names()
    ]


@register_pass("swiglu_fuse_pass")
class SwigluFusePass(Pass):
    """mul(x, Wg) -> swish  alongside  mul(x, Wu), joined by
    elementwise_mul  =>  ONE fused_swiglu op (the gpt2 use_swiglu FFN
    diamond).  The fused lowering runs both projections of a row tile
    and the gate product against ONE resident x tile
    (pallas_kernels.matmul_swiglu under FLAGS_use_pallas), so the gate
    and up pre-activations never reach HBM.  Conservative: beta-1
    swish, same x input and flatten dims on both muls, 2-D same-shape
    weights, single-consumer intermediates (checked across ALL blocks),
    protected fetches respected."""

    def apply(self, program, scope=None):
        block = program.global_block()
        n = 0
        changed = True
        while changed:
            changed = False
            from ..analysis.graph import producer_ops

            producers = producer_ops(block)
            for emul in list(block.ops):
                if emul.type != "elementwise_mul":
                    continue
                if int(emul.attrs.get("axis", -1)) != -1:
                    continue
                xn = emul.inputs.get("X", [None])[0]
                yn = emul.inputs.get("Y", [None])[0]
                if xn is None or yn is None:
                    continue
                hit = None
                for gate_out, up_out in ((xn, yn), (yn, xn)):
                    act = producers.get(gate_out)
                    umul = producers.get(up_out)
                    if (act is None or act.type != "swish"
                            or umul is None or umul.type != "mul"):
                        continue
                    if float(act.attrs.get("beta", 1.0)) != 1.0:
                        continue
                    gmul = producers.get(act.inputs["X"][0])
                    if gmul is None or gmul.type != "mul":
                        continue
                    if gmul.inputs["X"][0] != umul.inputs["X"][0]:
                        continue  # both sides must project the SAME x
                    ncd = int(gmul.attrs.get("x_num_col_dims", 1))
                    if ncd != int(umul.attrs.get("x_num_col_dims", 1)):
                        continue
                    if (int(gmul.attrs.get("y_num_col_dims", 1)) != 1
                            or int(umul.attrs.get("y_num_col_dims", 1))
                            != 1):
                        continue
                    wg = block._find_var_recursive(gmul.inputs["Y"][0])
                    wu = block._find_var_recursive(umul.inputs["Y"][0])
                    if (wg is None or wu is None or wg.shape is None
                            or wu.shape is None or len(wg.shape) != 2
                            or list(wg.shape) != list(wu.shape)):
                        continue
                    # every intermediate single-consumer, ALL blocks
                    inter = [(gmul.outputs["Out"][0], act),
                             (act.outputs["Out"][0], emul),
                             (umul.outputs["Out"][0], emul)]
                    if any(
                        _consumers_all_blocks(program, name) != [consumer]
                        for name, consumer in inter
                    ):
                        continue
                    chain = [gmul, act, umul, emul]
                    if not _chain_safe(program, chain):
                        continue
                    hit = (gmul, act, umul, ncd)
                    break
                if hit is None:
                    continue
                gmul, act, umul, ncd = hit
                fused = _mk_op(
                    block, "fused_swiglu",
                    {"X": [gmul.inputs["X"][0]],
                     "GateW": gmul.inputs["Y"],
                     "UpW": umul.inputs["Y"]},
                    {"Out": [emul.outputs["Out"][0]]},
                    {"x_num_col_dims": ncd},
                )
                # insert at the elementwise_mul's slot: every fused
                # input is defined there; the chain need not be
                # contiguous
                block.ops.insert(block.ops.index(emul), fused)
                for op in (gmul, act, umul, emul):
                    block.ops.remove(op)
                program._bump_version()
                n += 1
                changed = True
                break
        program._swiglu_fused_count = n
        return program


@register_pass("residual_ln_fuse_pass")
class ResidualLnFusePass(Pass):
    """elementwise_add(x, y) -> layer_norm  =>  ONE fused_residual_ln op
    whose lowering forms the sum as the LN kernel's PROLOGUE
    (pallas_kernels.fused_add_layer_norm under FLAGS_use_pallas).  The
    SUM stays a real output under its original name, AND the fused op
    lands at the ADD's position — so every other consumer of the sum
    (gpt2: the add feeds BOTH the norm and the next residual add) reads
    a value defined exactly where it used to be, wherever that consumer
    sits.  Conservative: same-shape known operands (a residual add, not
    a broadcast bias), trailing-axis norm with Scale+Bias, exactly one
    layer_norm consumer of the sum in the global block, protected
    fetches respected."""

    def apply(self, program, scope=None):
        block = program.global_block()
        n = 0
        changed = True
        while changed:
            changed = False
            for add in list(block.ops):
                if add.type != "elementwise_add":
                    continue
                if int(add.attrs.get("axis", -1)) != -1:
                    continue
                xn = add.inputs.get("X", [None])[0]
                yn = add.inputs.get("Y", [None])[0]
                xv = block._find_var_recursive(xn) if xn else None
                yv = block._find_var_recursive(yn) if yn else None
                if (xv is None or yv is None or xv.shape is None
                        or yv.shape is None
                        or list(xv.shape) != list(yv.shape)
                        or any(int(d) < 0 for d in xv.shape[1:])):
                    continue
                add_out = add.outputs["Out"][0]
                cons = _consumers_all_blocks(program, add_out)
                lns = [c for c in cons if c.type == "layer_norm"
                       and c.inputs.get("X", [None])[0] == add_out
                       and c in block.ops]
                if len(lns) != 1:
                    continue
                ln = lns[0]
                rank = len(xv.shape)
                if int(ln.attrs.get("begin_norm_axis", 1)) != rank - 1:
                    continue
                if not (ln.inputs.get("Scale") and ln.inputs.get("Bias")):
                    continue
                chain = [add, ln]
                if not _chain_safe(program, chain):
                    continue
                outputs = {
                    "Sum": [add_out],
                    "Y": list(ln.outputs.get("Y", [])),
                }
                for slot in ("Mean", "Variance"):
                    if ln.outputs.get(slot):
                        outputs[slot] = list(ln.outputs[slot])
                fused = _mk_op(
                    block, "fused_residual_ln",
                    {"X": [xn], "Y": [yn],
                     "Scale": list(ln.inputs["Scale"]),
                     "Bias": list(ln.inputs["Bias"])},
                    outputs,
                    {"epsilon": float(ln.attrs.get("epsilon", 1e-5)),
                     "begin_norm_axis": rank - 1},
                )
                # land at the ADD's index (inputs defined there; Sum
                # defined exactly where it used to be)
                block.ops.insert(block.ops.index(add), fused)
                block.ops.remove(add)
                block.ops.remove(ln)
                program._bump_version()
                n += 1
                changed = True
                break
        program._residual_ln_fused_count = n
        return program


@register_pass("linear_xent_fuse_pass")
class LinearXentFusePass(Pass):
    """The logits-free loss rewrite: the final vocab projection
    (mul, or matmul(transpose_Y) for tied embeddings) feeding
    softmax_with_cross_entropy (hard label) or smooth_label_xent
    becomes ONE fused_linear_xent op — under FLAGS_use_pallas the
    [R, V] f32 logits tensor (and its gradient twin) never exists in
    HBM (pallas_kernels.fused_linear_xent streams vocab tiles through
    an online logsumexp; the backward recomputes per-tile softmax
    against W).  Conservative: 2-D weight, hard labels, no
    ignore_index, the xent's Softmax output unused ANYWHERE (all
    blocks), single-consumer logits, protected fetches respected.

    Label contract: OUT-OF-RANGE hard labels (stray pad ids) get zero
    loss and zero gradient after fusion — the fused op's documented
    one_hot convention.  The unfused chains never agreed on this case
    (dense clamps the gather, the softmax_xent kernel yields lse), so
    the pass normalizes an undefined behavior rather than changing a
    defined one; in-range labels are unaffected
    (test_fused_linear_xent_out_of_range_label_convention)."""

    def apply(self, program, scope=None):
        block = program.global_block()

        def fuse(chain):
            proj, xent = chain
            if proj.type == "mul":
                if int(proj.attrs.get("y_num_col_dims", 1)) != 1:
                    return False
                w_name, transpose_w = proj.inputs["Y"][0], False
                x_name = proj.inputs["X"][0]
                # the lowering flattens x as [..., H] -> [R, H]: only a
                # mul whose row/contraction split is at the LAST axis
                # matches (x_num_col_dims == rank-1)
                xv = block._find_var_recursive(x_name)
                if xv is None or xv.shape is None:
                    return False
                if int(proj.attrs.get("x_num_col_dims", 1)) != \
                        len(xv.shape) - 1:
                    return False
            else:  # matmul: only the tied-embedding x @ W^T form
                if (not proj.attrs.get("transpose_Y", False)
                        or proj.attrs.get("transpose_X", False)
                        or float(proj.attrs.get("alpha", 1.0)) != 1.0):
                    return False
                w_name, transpose_w = proj.inputs["Y"][0], True
                x_name = proj.inputs["X"][0]
            wv = block._find_var_recursive(w_name)
            if wv is None or wv.shape is None or len(wv.shape) != 2:
                return False
            logits_name = proj.outputs["Out"][0]
            if xent.inputs.get("Logits", [None])[0] != logits_name:
                return False
            if xent.type == "softmax_with_cross_entropy":
                if bool(xent.attrs.get("soft_label", False)):
                    return False
                if int(xent.attrs.get("ignore_index", -100)) >= 0:
                    return False
                softmax_out = xent.outputs.get("Softmax", [None])[0]
                if softmax_out:
                    protected = getattr(
                        program, "_protected_fetch_names", ())
                    if softmax_out in protected or _consumers_all_blocks(
                            program, softmax_out, exclude=(xent,)):
                        return False
                eps = 0.0
            else:  # smooth_label_xent reads raw int labels already
                eps = float(xent.attrs.get("epsilon", 0.0))
            # logits single-consumer across ALL blocks (OpPattern only
            # scans the global block)
            if _consumers_all_blocks(program, logits_name,
                                     exclude=(xent,)):
                return False
            if not _chain_safe(program, chain):
                return False
            fused = _mk_op(
                block, "fused_linear_xent",
                {"X": [x_name], "W": [w_name],
                 "Label": list(xent.inputs["Label"])},
                {"Loss": list(xent.outputs["Loss"])},
                {"epsilon": eps, "transpose_w": transpose_w},
            )
            _replace_chain(block, program, chain, [fused])
            return True

        n = 0
        for head in ("mul", "matmul"):
            for tail in ("softmax_with_cross_entropy", "smooth_label_xent"):
                n += OpPattern([head, tail]).rewrite(block, fuse)
        program._linear_xent_fused_count = n
        return program


@register_pass("matmul_epilogue_fuse_pass")
def _matmul_epilogue_fuse(program, scope):
    """The training-program epilogue bundle (ROADMAP item 1): fc
    (mul+bias+act), SwiGLU diamonds, and residual-add+layer_norm pairs
    collapse into their fused ops so the model builders get the pallas
    matmul-epilogue kernels without model edits.  Apply BEFORE
    Optimizer.minimize (grad ops must differentiate through the fused
    ops) and before any AMP rewrite."""
    from .pass_registry import apply_pass

    for name in ("fc_fuse_pass", "swiglu_fuse_pass",
                 "residual_ln_fuse_pass"):
        apply_pass(program, name, scope=scope)
    program._matmul_epilogue_fused_count = (
        getattr(program, "_fc_fused_count", 0)
        + getattr(program, "_swiglu_fused_count", 0)
        + getattr(program, "_residual_ln_fused_count", 0))
    return program
