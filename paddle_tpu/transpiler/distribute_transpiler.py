"""DistributeTranspiler: Program -> (trainer program, pserver programs).

API-compatible re-design of the reference transpiler
(python/paddle/fluid/transpiler/distribute_transpiler.py:239 transpile,
:473 get_trainer_program, :592 get_pserver_program, :853 get_startup_program)
for the TPU execution model:

* Parameters/grads are sliced into flat blocks (slice_variable :80 analog)
  and placed on pservers by a PSDispatcher (RoundRobin default).
* The trainer program keeps forward+backward+clip/regularization, drops the
  optimizer ops, and gains `send` / `send_barrier` / `recv` /
  `fetch_barrier` ops — which lower to ordered host callbacks inside the
  one compiled XLA step (see ops/dist_ops.py) instead of gRPC runtime ops.
* Each pserver program is a single `listen_and_serv` op whose "optimize
  sub-blocks" are serialized shard Programs (one per param block) that the
  pserver compiles once and applies per round (see distributed/ps_server).
* Grads are pre-scaled by 1/num_trainers on the trainer so that the
  pserver's per-round sum equals the global-batch mean gradient: a sync
  N-trainer run matches the equivalent local run exactly.
* "nccl2" mode (collective DP over DCN, gen_nccl_id_op.cc analog) needs no
  program rewrite here: transpile records the job layout and
  distributed.init_collective / parallel.DistributedExecutor run the same
  program under pjit with jax.distributed-initialized hosts.
"""

import math

from .. import framework, unique_name
from ..framework import Program
from .ps_dispatcher import RoundRobin, SizeWeighted, PSDispatcher


class DistributeTranspilerConfig:
    """Knob surface of the reference config (distribute_transpiler.py:126)."""

    slice_var_up = True
    # size-weighted greedy bin-pack: uneven param sizes spread by load,
    # not by position (RoundRobin / HashName stay selectable)
    split_method = SizeWeighted
    min_block_size = 8192
    # "pserver": dense+sparse round-trip through parameter servers;
    # "nccl2": program unchanged, layout recorded for init_collective;
    # "collective": dense grad sync lowers INTO the compiled step as
    #   c_allreduce_* ops over a parallel/mesh dp mesh (no pserver in the
    #   dense path), while sparse/embedding traffic — when the model has
    #   distributed lookup tables — keeps the pserver (hybrid mode)
    mode = "pserver"  # "pserver" | "nccl2" | "collective"
    # mesh axis the collective mode's allreduces ride (executor binds the
    # same axis when it runs the program over the dp mesh)
    collective_axis = "dp"
    print_log = False
    # byte cap per coalesced comm bucket; None defers to
    # FLAGS_comm_bucket_bytes, 0 restores per-variable send/recv ops
    comm_bucket_bytes = None
    # wire dtype for dense bucket grads + fetched params ("float32" |
    # "bfloat16"); None defers to FLAGS_comm_wire_dtype.  Stamped into
    # the bucket ops so both ends agree per bucket plan; the legacy
    # per-variable path always ships full precision.
    comm_wire_dtype = None
    # int8 + error-feedback compression for dense bucket grads; None
    # defers to FLAGS_comm_grad_int8 (see ops/dist_ops.py)
    comm_grad_int8 = None


class VarBlock:
    def __init__(self, varname, idx, begin, end):
        self.varname = varname
        self.idx = idx
        self.begin = begin  # flat element offset
        self.end = end

    @property
    def size(self):
        return self.end - self.begin

    @property
    def block_name(self):
        return "%s.block%d" % (self.varname, self.idx)


def _dtype_nbytes(dtype):
    """Per-element bytes for bucket budgeting (bf16 and friends whose
    dtype string numpy can't parse budget as 4 — a cap heuristic, not a
    wire format)."""
    import numpy as np

    try:
        return int(np.dtype(str(dtype)).itemsize)
    except TypeError:
        return 4


def pack_buckets(entries, cap_bytes):
    """Greedy size-capped packing: `entries` is [(nbytes, payload), ...];
    returns a list of buckets (lists of payloads), each bucket's total
    ≤ cap_bytes except when a single entry alone exceeds the cap (it gets
    its own bucket — a block is never split below the slice plan)."""
    buckets = []
    cur, cur_bytes = [], 0
    for nbytes, payload in entries:
        if cur and cur_bytes + nbytes > cap_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(payload)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def slice_variable(var_numels, slice_count, min_block_size=8192):
    """Split each var's flat numel into at most `slice_count` blocks of at
    least `min_block_size` elements (reference slice_variable :80)."""
    out = {}
    for name, numel in var_numels:
        max_blocks = max(1, int(math.ceil(numel / float(min_block_size))))
        split_count = max(1, min(slice_count, max_blocks))
        block_size = int(math.ceil(numel / float(split_count)))
        blocks = []
        off = 0
        idx = 0
        while off < numel:
            end = min(off + block_size, numel)
            blocks.append(VarBlock(name, idx, off, end))
            off = end
            idx += 1
        out[name] = blocks
    return out


def derive_plan(spec, world=None, split_method=None):
    """The declarative plan function (elastic autoscaling,
    docs/FAULT_TOLERANCE.md "Elastic autoscaling"): a PURE function of
    (param set, world size, endpoints, flags) -> the complete comm plan
    — block slicing, block->endpoint dispatch, per-endpoint send/recv
    buckets with their folded-barrier totals, and the grad scale.

    The SAME function runs at transpile time (DistributeTranspiler
    consumes its output verbatim) and at re-plan time (ops/dist_ops.py
    re-derives when a pserver mints a new plan epoch), so for an
    unchanged world the runtime-derived plan is BIT-IDENTICAL to the
    transpile-time plan — the contract the chaos tests pin.

    `spec` is the JSON-able plan spec the transpiler carries in the
    program (see DistributeTranspiler.plan_spec):
      {"params": [[param, shape, dtype, grad], ...],   # ordered
       "endpoints": [...], "trainers": N,
       "flags": {"slice_var_up", "min_block_size", "split_method",
                 "comm_bucket_bytes", "comm_wire_dtype",
                 "comm_grad_int8"}}
    `world` overrides {"trainers": ..., "endpoints": [...]} for a
    re-plan; `split_method` may pass the dispatcher class directly
    (otherwise it resolves by name from ps_dispatcher — the spec stays
    declarative).

    Shard stability (live pserver migration, docs/FAULT_TOLERANCE.md
    "Live shard migration"): block SLICING always uses the spec's BASE
    endpoint count, so block boundaries and names are invariant under a
    pserver-set change — only the block->endpoint DISPATCH moves.  A
    shard is therefore a stable, nameable unit of state that migration
    can hand whole from one server to another; re-slicing would instead
    change what a "shard" is and make handoff a global re-scatter.  For
    an unchanged world this is byte-identical to the old rule (live ==
    base).  The same rule covers sparse tables: `sparse_eps[s]` maps the
    BASE shard index s (row g lives in shard g % n_base forever) onto
    the live endpoint set."""
    from . import ps_dispatcher

    world = world or {}
    base_eps = [str(e) for e in spec["endpoints"]]
    endpoints = [str(e) for e in
                 (world.get("endpoints") or spec["endpoints"])]
    trainers = int(world.get("trainers") or spec["trainers"])
    flags = spec.get("flags") or {}
    if split_method is None:
        split_method = getattr(ps_dispatcher,
                               str(flags.get("split_method",
                                             "SizeWeighted")))
    params = [(str(p), [int(d) for d in shape], str(dtype), str(g))
              for p, shape, dtype, g in spec["params"]]

    numels = []
    for p, shape, _dt, _g in params:
        numel = 1
        for d in shape:
            numel *= int(d)
        numels.append((p, numel))
    # slicing keys off the BASE world: stable shard identity (see above)
    slice_count = len(base_eps) if flags.get("slice_var_up", True) else 1
    blocks = slice_variable(numels, slice_count,
                            int(flags.get("min_block_size", 8192)))
    dispatcher = split_method(endpoints)
    block_eps = {}
    for p, _shape, _dt, _g in params:
        for blk, ep in zip(blocks[p], dispatcher.dispatch(blocks[p])):
            block_eps[(p, blk.idx)] = ep

    plan = {
        "endpoints": endpoints,
        "trainers": trainers,
        # each trainer pre-scales grads by 1/world so the pserver's
        # per-round sum is the global-batch mean — THE value a re-plan
        # exists to correct when membership changes durably
        "grad_scale": 1.0 / float(trainers),
        "blocks": blocks,
        "block_eps": block_eps,
        # sparse shard s (stable: rows hash g % n_base) -> live endpoint.
        # Identity for an unchanged world (s % n == s), deterministic
        # round-robin of the stable shards over a changed one.
        "sparse_eps": [endpoints[s % len(endpoints)]
                       for s in range(len(base_eps))],
    }
    bucket_bytes = int(flags.get("comm_bucket_bytes", 0))
    if bucket_bytes <= 0:
        return plan

    # ---- send buckets (grad push) — _plan_send_buckets's exact layout
    per_ep = {ep: [] for ep in endpoints}
    for xi, (p, _shape, dtype, g) in enumerate(params):
        isz = _dtype_nbytes(dtype)
        for blk in blocks[p]:
            ep = block_eps[(p, blk.idx)]
            per_ep[ep].append(
                (blk.size * isz,
                 [xi, blk.begin, blk.end, "%s.block%d" % (g, blk.idx)]))
    send_buckets = []
    for ep in endpoints:
        got = pack_buckets(per_ep[ep], bucket_bytes)
        for bucket in got or [[]]:  # empty bucket = folded barrier for
            send_buckets.append([ep, bucket])  # block-less endpoints
    sync_totals = {}
    for ep, _entries in send_buckets:
        sync_totals[ep] = sync_totals.get(ep, 0) + 1
    plan["send_buckets"] = send_buckets
    plan["sync_totals"] = sync_totals

    # ---- recv buckets (param pull) — _plan_recv_buckets's exact layout
    per_ep = {ep: [] for ep in endpoints}
    params_spec = []
    for p, shape, dtype, _g in params:
        isz = _dtype_nbytes(dtype)
        bnames = []
        for blk in blocks[p]:
            ep = block_eps[(p, blk.idx)]
            per_ep[ep].append((blk.size * isz, blk.block_name))
            bnames.append(blk.block_name)
        params_spec.append([p, list(shape), dtype, bnames])
    recv_buckets = []
    for ep in endpoints:
        got = pack_buckets(per_ep[ep], bucket_bytes)
        for bucket in got or [[]]:
            recv_buckets.append([ep, bucket])
    fetch_totals = {}
    for ep, _names in recv_buckets:
        fetch_totals[ep] = fetch_totals.get(ep, 0) + 1
    plan["params_spec"] = params_spec
    plan["recv_buckets"] = recv_buckets
    plan["fetch_totals"] = fetch_totals
    return plan


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        if isinstance(self.config.split_method, type):
            assert issubclass(self.config.split_method, PSDispatcher)

    # ------------------------------------------------------------------
    def transpile(
        self,
        trainer_id,
        program=None,
        pservers="127.0.0.1:6174",
        trainers=1,
        sync_mode=True,
        startup_program=None,
        current_endpoint="",
    ):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        self.origin_program = program or framework.default_main_program()
        self.startup_program = (
            startup_program or framework.default_startup_program()
        )
        if isinstance(pservers, str):
            self.pserver_endpoints = [
                ep.strip() for ep in pservers.split(",") if ep.strip()
            ]
        else:
            self.pserver_endpoints = list(pservers)

        if self.config.mode == "nccl2":
            # layout-only mode: program unchanged; record layout for
            # distributed.init_collective (gen_nccl_id handshake analog is
            # jax.distributed.initialize over DCN)
            self.nccl2_trainer_endpoints = self.pserver_endpoints
            return

        self._resolve_comm_config()
        if self.config.mode == "collective":
            self._transpile_collective_mode()
            return

        self._transpile_pserver_mode()

    def _resolve_comm_config(self):
        """Resolve the wire-compression knobs ONCE, up front: every role
        (trainer bucket ops, sparse send ops, pserver replies via the
        request's declaration) must agree on the wire form for this job,
        and sparse rewrites run before the dense tail is planned."""
        from ..flags import get_flag as _gf

        bucket_bytes = self.config.comm_bucket_bytes
        if bucket_bytes is None:
            bucket_bytes = _gf("comm_bucket_bytes")
        self.comm_bucket_bytes = int(bucket_bytes)
        wire_dtype = self.config.comm_wire_dtype
        if wire_dtype is None:
            wire_dtype = _gf("comm_wire_dtype")
        wire_dtype = str(wire_dtype)
        if wire_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "comm_wire_dtype must be 'float32' or 'bfloat16', got %r "
                "(int8 grads are the separate FLAGS_comm_grad_int8 gate)"
                % (wire_dtype,))
        self.comm_wire_dtype = wire_dtype
        grad_int8 = self.config.comm_grad_int8
        if grad_int8 is None:
            grad_int8 = _gf("comm_grad_int8")
        self.comm_grad_int8 = bool(grad_int8)

    # ------------------------------------------------------------------
    def _params_grads_from_roles(self):
        """(param, grad) name pairs off the optimize ops' op_role_var tags
        — the OpRole mechanism the reference transpiler is driven by.
        Distributed lookup tables are excluded (their ops were rewritten to
        prefetch/send_sparse before this runs)."""
        sparse = set(getattr(self, "sparse_tables", {}))
        pairs = []
        seen = set()
        for op in self.origin_program.global_block().ops:
            if op.attrs.get("op_role") != "optimize":
                continue
            rv = op.attrs.get("op_role_var")
            if not rv or len(rv) < 2:
                continue
            if rv[0] not in seen and rv[0] not in sparse:
                seen.add(rv[0])
                pairs.append((rv[0], rv[1]))
        return pairs

    # ------------------------------------------------------------------
    def _handle_distributed_lookup(self):
        """Distributed lookup table (§2.9 row 4: lookup_table with
        is_distributed, prefetch_op + split/merge_ids analog).

        The table's rows shard round-robin over the pservers (global row g
        lives on server g%N at local index g//N).  Rewrite, in place:
          * lookup_table{is_distributed} -> `prefetch` (host callback that
            routes ids to their servers and merges rows back),
          * lookup_table_grad            -> `send_sparse` (rows pushed back
            to the owning server; in SYNC mode the server queues them and
            applies ONE merged optimizer update at the round barrier —
            the reference's optimizer-sub-block-at-barrier semantics —
            while ASYNC mode applies on arrival),
          * the table's optimizer op is dropped here and replayed
            server-side per shard (sgd/adagrad/adam, see
            ps_server._apply_sparse).
        """
        block = self.origin_program.global_block()
        eps = self.pserver_endpoints
        n = len(eps)
        tables = set()
        for op in block.ops:
            if op.type == "lookup_table" and op.attrs.get("is_distributed"):
                tables.add(op.inputs["W"][0])
        self.sparse_tables = {}
        self.sparse_token_vars = []
        if not tables:
            return

        # capture each table's (dropped) optimizer op: type + hyperparams
        # + learning rate.  The pserver replays the same sparse update
        # rule per shard (the reference runs the full optimizer sub-block
        # on the pserver, sparse rows included —
        # distribute_transpiler.py:592 get_pserver_program,
        # listen_and_serv_op.cc:106).  lr may be a startup constant, a
        # per-param `scale` of one, or a SCHEDULED var — schedules move to
        # the pserver's lr_program, so the sparse update reads the decayed
        # value from the pserver scope at apply time (lr_name).
        startup_fills = {}
        for op in self.startup_program.global_block().ops:
            if op.type == "fill_constant":
                for o in op.output_arg_names():
                    startup_fills[o] = float(op.attrs.get("value", 0.0))
        # per-param-lr helper: scaled-lr var -> (base lr var, factor)
        scale_map = {}
        for op in block.ops:
            if op.type == "scale" and op.attrs.get("op_role") == "optimize":
                scale_map[op.outputs["Out"][0]] = (
                    op.inputs["X"][0], float(op.attrs.get("scale", 1.0)))
        _SPARSE_OPT_DEFAULTS = {
            "sgd": {},
            "adagrad": {"epsilon": 1e-6},
            "adam": {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
            "momentum": {"mu": 0.9, "use_nesterov": False},
        }
        table_opt = {}
        for op in block.ops:
            rv = op.attrs.get("op_role_var")
            if op.attrs.get("op_role") == "optimize" and rv and rv[0] in tables:
                if op.type == "scale":
                    continue  # handled via scale_map
                if op.type not in _SPARSE_OPT_DEFAULTS:
                    raise NotImplementedError(
                        "distributed lookup table '%s' is optimized by '%s'; "
                        "the pserver applies sparse sgd/momentum/adagrad/adam "
                        "on its row shards — use one of those for "
                        "is_distributed embeddings" % (rv[0], op.type)
                    )
                lr_names = op.inputs.get("LearningRate", [])
                lr_name = lr_names[0] if lr_names else None
                lr_scale = 1.0
                if lr_name in scale_map:
                    lr_name, lr_scale = scale_map[lr_name]
                lr_const = startup_fills.get(lr_name or "")
                oattrs = {
                    k: float(op.attrs.get(k, d))
                    for k, d in _SPARSE_OPT_DEFAULTS[op.type].items()
                }
                table_opt[rv[0]] = {
                    "type": op.type,
                    "attrs": oattrs,
                    "lr_name": lr_name,
                    "lr_scale": lr_scale,
                    "lr_const": (lr_const * lr_scale
                                 if lr_const is not None else None),
                }

        for w in tables:
            v = block._find_var_recursive(w)
            opt = table_opt.get(
                w, {"type": "sgd", "attrs": {}, "lr_name": None,
                    "lr_scale": 1.0, "lr_const": 0.01})
            # lr stays None for a SCHEDULED lr (named var, no startup
            # constant): the pserver must read the decayed var and is
            # required to fail loudly if it ever goes missing, never
            # silently train at a stale constant
            self.sparse_tables[w] = {
                "shards": ["%s.shard%d" % (w, i) for i in range(n)],
                "emb_dim": int(v.shape[1]),
                "lr": opt["lr_const"],
                "opt": opt,
            }

        # collective (hybrid) mode: the pserver carries ONLY sparse
        # traffic, applied per-arrival (async semantics — there is no
        # dense round whose barrier could trigger a merged apply), and
        # the rpc ops run per mesh REPLICA (dynamic trainer rank from
        # lax.axis_index instead of the static process-wide id)
        hybrid = self.config.mode == "collective"
        # async pserver mode: stamp the fenced-delivery contract
        # (docs/FAULT_TOLERANCE.md, async section) — per-table seq tokens
        # on send_sparse (journaled + deduped server-side, re-shipped on
        # an incarnation bump), logical clocks on prefetch (bounded
        # staleness), and the hot-row cache's mirror rule when the
        # table's optimizer is client-mirrorable (sgd, constant lr)
        async_fence = (not self.sync_mode) and not hybrid

        def hot_opt_for(info):
            """Mirror spec for the trainer-side hot-row cache — or None
            when the client CANNOT mirror the server's apply exactly: a
            compressed sparse wire means the server applies the
            bf16-DECODED grad, not the values the client holds, so the
            cache would drift between refreshes and misattribute the
            rounding error to other trainers via the residual
            predictor.  (dist_ops additionally requires sgd + a
            constant lr.)"""
            if self.comm_wire_dtype != "float32":
                return None
            return {"type": info["opt"]["type"],
                    "lr": info["opt"].get("lr_const")}

        new_ops = []
        for op in block.ops:
            if (
                op.type == "lookup_table"
                and op.attrs.get("is_distributed")
            ):
                w = op.inputs["W"][0]
                info = self.sparse_tables[w]
                pre = framework.Operator(
                    block,
                    "prefetch",
                    None,
                    None,
                    {
                        "epmap": eps,
                        "table_names": info["shards"],
                        "emb_dim": info["emb_dim"],
                        "trainer_id": self.trainer_id,
                        "collective": hybrid,
                        "async_fence": async_fence,
                        "hot_opt": hot_opt_for(info),
                        "op_role": "rpc",
                    },
                )
                pre.inputs = {"Ids": list(op.inputs["Ids"])}
                pre.outputs = {"Out": list(op.outputs["Out"])}
                new_ops.append(pre)
            elif (
                op.type == "lookup_table_grad"
                and op.inputs.get("W", [None])[0] in tables
            ):
                w = op.inputs["W"][0]
                info = self.sparse_tables[w]
                dummy = block.create_var(
                    name=unique_name.generate(w + "@SPARSE_TOKEN"), shape=[1]
                )
                ss = framework.Operator(
                    block,
                    "send_sparse",
                    None,
                    None,
                    {
                        "epmap": eps,
                        "table_names": info["shards"],
                        "trainer_id": self.trainer_id,
                        "scale": 1.0 / float(self.trainer_num),
                        # sync rounds fence sparse chunks with the dense
                        # step token for restart replay (dist_ops); the
                        # hybrid collective path has no dense rounds, so
                        # its sparse chunks apply on arrival
                        "sync_mode": self.sync_mode and not hybrid,
                        "collective": hybrid,
                        "async_fence": async_fence,
                        "hot_opt": hot_opt_for(info),
                        # sparse row VALUES ride the planned wire dtype
                        # (ids/rows counts stay exact; bf16 halves the
                        # value payload — PR 5's documented f32-only gap)
                        "wire_dtype": self.comm_wire_dtype,
                        "op_role": "rpc",
                    },
                )
                ss.inputs = {
                    "Ids": list(op.inputs["Ids"]),
                    "Grad": list(op.inputs["Out@GRAD"]),
                }
                ss.outputs = {"Out": [dummy.name]}
                self.sparse_token_vars.append(dummy.name)
                new_ops.append(ss)
            elif (
                op.attrs.get("op_role") == "optimize"
                and op.attrs.get("op_role_var")
                and op.attrs["op_role_var"][0] in tables
            ):
                continue  # the sparse update happens server-side
            else:
                new_ops.append(op)
        block.ops = new_ops

    def _transpile_pserver_mode(self):
        block = self.origin_program.global_block()
        eps = self.pserver_endpoints
        self._handle_distributed_lookup()
        self.params_grads = self._params_grads_from_roles()
        if not self.params_grads:
            raise ValueError(
                "no optimizer ops found — call optimizer.minimize(loss) "
                "before transpile()"
            )

        # ---- partition (via the declarative plan spec) -----------------
        # The whole comm plan — block slicing, dispatch, buckets, grad
        # scale — is a pure function of this JSON-able spec
        # (derive_plan), so the runtime can re-derive it when membership
        # changes (elastic autoscaling): the spec is carried in the
        # program / stamped onto the rpc ops instead of the plan being
        # baked-only into attrs.  For the unchanged world derive_plan's
        # output here IS the stamped plan, bit for bit.
        self._param_vars = {}
        for p, _g in self.params_grads:
            self._param_vars[p] = block._find_var_recursive(p)
        split_name = (self.config.split_method.__name__
                      if isinstance(self.config.split_method, type)
                      else type(self.config.split_method).__name__)
        self.plan_spec = {
            "params": [
                [p, [int(d) for d in self._param_vars[p].shape],
                 str(self._param_vars[p].dtype), g]
                for p, g in self.params_grads],
            "endpoints": list(eps),
            "trainers": int(self.trainer_num),
            "flags": {
                "slice_var_up": bool(self.config.slice_var_up),
                "min_block_size": int(self.config.min_block_size),
                "split_method": split_name,
                "comm_bucket_bytes": int(self.comm_bucket_bytes),
                "comm_wire_dtype": str(self.comm_wire_dtype),
                "comm_grad_int8": bool(self.comm_grad_int8),
            },
        }
        self.plan_gid = unique_name.generate("dist_plan")
        plan = derive_plan(self.plan_spec,
                           split_method=self.config.split_method)
        self.param_blocks = plan["blocks"]
        self.block_eps = plan["block_eps"]  # (param, idx) -> endpoint
        self.origin_program._dist_plan_spec = self.plan_spec
        # elasticity needs the spec to be self-contained: a CUSTOM
        # dispatcher class is not resolvable by name at re-plan time
        # (derive_plan looks it up in ps_dispatcher), so the plan stays
        # static for this job rather than crashing the runtime re-plan
        # mid-round; same for the legacy per-variable wire, which has
        # no plan-carrying ops at all
        from .ps_dispatcher import RoundRobin as _rr  # noqa: F401
        from . import ps_dispatcher as _pd

        self._plan_elastic = (
            getattr(_pd, split_name, None) is self.config.split_method
            and self.comm_bucket_bytes > 0)
        if not self._plan_elastic:
            import sys

            sys.stderr.write(
                "WARNING: this job's comm plan is NOT runtime-"
                "re-derivable (%s) — membership changes will not "
                "re-scale gradients (docs/FAULT_TOLERANCE.md "
                "'Elastic autoscaling')\n" % (
                    "custom split_method %r is not resolvable by name "
                    "at re-plan time" % split_name
                    if self.comm_bucket_bytes > 0 else
                    "the legacy per-variable wire "
                    "(comm_bucket_bytes=0) carries no plan spec"))

        # ---- split optimizer ops off the trainer ----------------------
        self.optimize_ops = [
            op for op in block.ops if op.attrs.get("op_role") == "optimize"
        ]
        self.lr_ops = [
            op for op in block.ops if op.attrs.get("op_role") == "lrsched"
        ]
        drop = set(id(op) for op in self.optimize_ops + self.lr_ops)
        block.ops = [op for op in block.ops if id(op) not in drop]

        # ---- append trainer-side rpc ops ------------------------------
        # bucketed path (default): one size-capped coalesced frame per
        # bucket per pserver + windowed in-flight RPC, instead of one
        # round trip per variable.  comm_bucket_bytes=0 (config or flag)
        # restores the legacy per-var send/recv ops.  Wire-compression
        # metadata (comm_wire_dtype / comm_grad_int8) was resolved by
        # _resolve_comm_config before the sparse rewrite ran.
        with self.origin_program._op_role_guard("rpc"):
            scaled_names = []
            for p, g in self.params_grads:
                scaled = block.create_var(
                    name=g + "@DIST_SCALED",
                    shape=block._find_var_recursive(g).shape
                    if block._find_var_recursive(g)
                    else self._param_vars[p].shape,
                    dtype=self._param_vars[p].dtype,
                )
                block.append_op(
                    "scale",
                    inputs={"X": [g]},
                    outputs={"Out": [scaled.name]},
                    attrs={"scale": 1.0 / float(self.trainer_num)},
                )
                scaled_names.append(scaled.name)
            if self.comm_bucket_bytes > 0:
                self.send_bucket_plan = plan["send_buckets"]
                # sync mode folds the barriers into the bucket stream:
                # the server treats a trainer's LAST send bucket as its
                # send barrier and the last served get bucket as its
                # fetch barrier, so no dedicated barrier round trips
                sync_totals = plan["sync_totals"]
                dummy = block.create_var(name="@SEND_BUCKET_TOKEN",
                                         shape=[1])
                block.append_op(
                    "send_bucket",
                    inputs={"X": scaled_names},
                    outputs={"Out": [dummy.name]},
                    attrs={
                        "buckets": self.send_bucket_plan,
                        "sync_totals": sync_totals if self.sync_mode
                        else {},
                        "wire_dtype": self.comm_wire_dtype,
                        "grad_int8": self.comm_grad_int8,
                        # async mode: aseq-fenced buckets — journaled
                        # server-side, deduped across a restart
                        "async_fence": not self.sync_mode,
                        # elastic autoscaling: the declarative spec this
                        # plan derives from rides the op, so the runtime
                        # can re-derive it for a new world size when a
                        # pserver mints a new plan epoch (None when the
                        # spec is not self-contained — custom dispatcher)
                        "plan_spec": (self.plan_spec
                                      if self._plan_elastic else None),
                        "plan_gid": self.plan_gid,
                        "trainer_id": self.trainer_id,
                    },
                )
            else:
                for (p, g), sname in zip(self.params_grads, scaled_names):
                    blocks = self.param_blocks[p]
                    sections = [b.size for b in blocks]
                    epmap = [self.block_eps[(p, b.idx)] for b in blocks]
                    gblocks = ["%s.block%d" % (g, b.idx) for b in blocks]
                    dummy = block.create_var(name=g + "@SEND_TOKEN",
                                             shape=[1])
                    block.append_op(
                        "send",
                        inputs={"X": [sname]},
                        outputs={"Out": [dummy.name]},
                        attrs={
                            "sections": sections,
                            "epmap": epmap,
                            "block_names": gblocks,
                            "trainer_id": self.trainer_id,
                        },
                    )
            if self.sync_mode and not self.comm_bucket_bytes > 0:
                tok = block.create_var(name="@SEND_BARRIER_TOKEN", shape=[1])
                block.append_op(
                    "send_barrier",
                    outputs={"Out": [tok.name]},
                    attrs={"endpoints": eps, "trainer_id": self.trainer_id},
                )
            if self.comm_bucket_bytes > 0:
                self.recv_bucket_plan = plan["recv_buckets"]
                block.append_op(
                    "recv_bucket",
                    outputs={"Out": [p for p, _g in self.params_grads]},
                    attrs={
                        "params": plan["params_spec"],
                        "buckets": plan["recv_buckets"],
                        "fetch_totals": plan["fetch_totals"]
                        if self.sync_mode else {},
                        "wire_dtype": self.comm_wire_dtype,
                        "plan_spec": (self.plan_spec
                                      if self._plan_elastic else None),
                        "plan_gid": self.plan_gid,
                        "trainer_id": self.trainer_id,
                    },
                )
            else:
                for p, g in self.params_grads:
                    blocks = self.param_blocks[p]
                    pv = self._param_vars[p]
                    block.append_op(
                        "recv",
                        outputs={"Out": [p]},
                        attrs={
                            "sections": [b.size for b in blocks],
                            "epmap": [self.block_eps[(p, b.idx)]
                                      for b in blocks],
                            "block_names": [b.block_name for b in blocks],
                            "shape": [int(d) for d in pv.shape],
                            "dtype": str(pv.dtype),
                            "trainer_id": self.trainer_id,
                        },
                    )
            if self.sync_mode and not self.comm_bucket_bytes > 0:
                tok = block.create_var(name="@FETCH_BARRIER_TOKEN", shape=[1])
                block.append_op(
                    "fetch_barrier",
                    outputs={"Out": [tok.name]},
                    attrs={"endpoints": eps, "trainer_id": self.trainer_id},
                )
        # elastic stamps for the sparse rpc ops (created by the lookup
        # rewrite before the plan spec existed): the runtime scale
        # correction keys off the plan group, and async clock-only
        # chunks coalesce per (trainer, endpoint, step) across ALL the
        # program's send_sparse ops — clk_ops is the group size the
        # runtime counts arrivals against
        n_sparse = sum(1 for op in block.ops if op.type == "send_sparse")
        for op in block.ops:
            if op.type == "send_sparse":
                op.attrs["plan_gid"] = self.plan_gid
                op.attrs["plan_spec"] = (self.plan_spec
                                         if self._plan_elastic else None)
                if op.attrs.get("async_fence"):
                    op.attrs["clk_gid"] = self.plan_gid
                    op.attrs["clk_ops"] = n_sparse
            elif op.type == "prefetch":
                # live pserver migration: lookups re-route to a shard's
                # NEW owner off the same shared plan state (a stale read
                # gets a stale_plan reply, re-plans, and retries)
                op.attrs["plan_gid"] = self.plan_gid
                op.attrs["plan_spec"] = (self.plan_spec
                                         if self._plan_elastic else None)
        self.origin_program._bump_version()

    # ------------------------------------------------------------------
    def _transpile_collective_mode(self):
        """Collective data-parallel rewrite: dense gradient sync lowers
        INTO the compiled step as one ``c_allreduce_mean`` per dense grad
        (inserted between the ``*_grad`` output and the optimizer ops,
        which STAY on the trainer — every mesh replica applies the same
        averaged update to its replicated params), so XLA overlaps the
        all-reduce with backward compute and no Python runs in the dense
        grad path.  Hybrid: distributed lookup tables keep the pserver
        (prefetch / send_sparse exactly as today, applied per-arrival);
        their rows never ride the mesh.

        Replica semantics: each mesh shard is one logical trainer — it
        computes its shard-mean loss/grads, so the allreduce MEAN is the
        global-batch mean gradient (the pserver path's scale-by-1/N-then-
        sum, fused into one collective).  ``trainers`` is the mesh size.

        Hybrid ordering (no round barrier exists to provide it): each
        allreduce consumes the step's sparse send tokens via ``Deps``
        (the psum rendezvous then waits for every replica's sparse push),
        and each prefetch gains a ``Dep`` input on an allreduce-updated
        param — so step N's sparse rows are all on the pserver before any
        replica's step-N+1 lookup reads them, from pure data flow."""
        block = self.origin_program.global_block()
        self._handle_distributed_lookup()
        if self.sparse_tables and not self.pserver_endpoints:
            raise ValueError(
                "collective mode found distributed lookup tables %s but "
                "no pserver endpoints — hybrid mode keeps sparse traffic "
                "on pservers; pass pservers= (or drop is_distributed)"
                % sorted(self.sparse_tables))
        self.params_grads = self._params_grads_from_roles()
        if not self.params_grads:
            raise ValueError(
                "no dense optimizer ops found — call "
                "optimizer.minimize(loss) before transpile()"
            )
        # scheduled-lr sparse tables need the pserver-side lr_program,
        # which only dense rounds trigger — not available in hybrid mode
        for w, info in sorted(getattr(self, "sparse_tables", {}).items()):
            opt = info.get("opt") or {}
            if opt.get("lr_name") and info.get("lr") is None:
                raise NotImplementedError(
                    "hybrid collective mode cannot drive table %r's "
                    "SCHEDULED sparse learning rate: the pserver applies "
                    "rows per-arrival and runs no lr program (no dense "
                    "rounds) — use a constant lr for is_distributed "
                    "embeddings under mode='collective'" % w)

        axis = str(self.config.collective_axis)
        self.collective_axis = axis
        self.collective_nranks = int(self.trainer_num)
        tokens = list(getattr(self, "sparse_token_vars", []))
        new_ops, inserted = [], False
        grad_names = {g for _p, g in self.params_grads}
        allreduce_ops = []
        for p, g in self.params_grads:
            ar = framework.Operator(
                block, "c_allreduce_mean", None, None,
                {"axis_name": axis,
                 "nranks": self.collective_nranks,
                 "op_role": "backward",
                 "op_role_var": [p, g]},
            )
            # in-place on the grad var: every later reader (the
            # optimizer ops; grad clip ran earlier) sees the
            # cross-replica mean
            ar.inputs = {"X": [g]}
            if tokens:
                ar.inputs["Deps"] = tokens
            ar.outputs = {"Out": [g]}
            allreduce_ops.append(ar)
        for op in block.ops:
            if not inserted and op.attrs.get("op_role") == "optimize":
                new_ops.extend(allreduce_ops)
                inserted = True
            if (op.type == "prefetch" and op.attrs.get("collective")
                    and self.params_grads):
                # cross-step edge: the lookup waits for the previous
                # step's (allreduce-gated) param update on this replica
                op.inputs["Dep"] = [self.params_grads[0][0]]
            new_ops.append(op)
        if not inserted:  # defensive: optimize role guaranteed above
            new_ops.extend(allreduce_ops)
        # sanity: every grad the rewrite targets is actually produced by
        # the ORIGINAL ops — the in-place allreduces are excluded, or the
        # check would see their own Out and could never fire
        produced = set()
        for op in block.ops:
            produced.update(op.output_arg_names())
        missing = sorted(grad_names - produced)
        if missing:
            raise RuntimeError(
                "collective rewrite: grads %s are consumed by optimizer "
                "ops but never produced" % missing)
        block.ops = new_ops
        # the executor keys its collective run path off this marker (the
        # mesh axis it must bind with shard_map around the traced step)
        self.origin_program._collective = {
            "axis": axis, "nranks": self.collective_nranks}
        self.origin_program._bump_version()

    # ------------------------------------------------------------------
    # (bucket planning lives in the module-level derive_plan: the same
    # pure function serves transpile time and runtime re-plans — an
    # endpoint that receives no blocks still gets one EMPTY bucket so it
    # carries the folded barrier, registers for heartbeats/complete, and
    # terminates at job end instead of waiting on contact that never
    # comes)

    # ------------------------------------------------------------------
    def get_trainer_program(self):
        return self.origin_program

    # ------------------------------------------------------------------
    def _shard_program_for(self, p, g, blk, opt_ops):
        """Build the per-block optimizer shard Program from ALL optimize
        ops tagged for this param (per-param-lr `scale` helpers included) —
        the reference's per-shard optimize sub-block
        (get_pserver_program :592).  Var classification:
          * param / grad            -> 1-D block slices
          * full-numel accumulators -> sliced like the param (moments)
          * mutated small state     -> per-block private copies (beta pows:
                                       must advance once per shard, not once
                                       per co-located shard)
          * temps produced in-group -> local non-persistable vars
          * everything else         -> shared whole vars (learning rate)
        """
        prog = Program()
        b = prog.global_block()
        pnumel = blk.end - blk.begin
        pblock_name = blk.block_name
        gblock_name = "%s.block%d" % (g, blk.idx)
        pdtype = self._param_vars[p].dtype
        full_numel = 1
        for d in self._param_vars[p].shape:
            full_numel *= int(d)

        src_block = self.origin_program.global_block()
        produced = set()
        for op in opt_ops:
            produced.update(op.output_arg_names())

        rename = {p: pblock_name, g: gblock_name}
        slice_srcs = {pblock_name: (p, blk.begin, blk.end, pdtype)}
        whole = []
        local_tmp = []

        def classify(n):
            if n in rename:
                return
            v = src_block._find_var_recursive(n)
            numel = 1
            for d in (v.shape if v is not None else [1]):
                numel *= int(d)
            dtype = v.dtype if v is not None else "float32"
            if v is not None and numel == full_numel and full_numel > 1:
                bn = "%s.block%d" % (n, blk.idx)
                rename[n] = bn
                slice_srcs[bn] = (n, blk.begin, blk.end, dtype)
            elif n in produced and (v is None or v.persistable):
                # mutated persistable state (beta pow accumulators)
                bn = "%s.block%d" % (n, blk.idx)
                rename[n] = bn
                slice_srcs[bn] = (n, 0, numel, dtype)
            elif n in produced:
                rename[n] = n
                local_tmp.append((n, v))
            else:
                rename[n] = n
                whole.append(n)

        for op in opt_ops:
            for names in list(op.inputs.values()) + list(op.outputs.values()):
                for n in names:
                    classify(n)

        # vars
        b.create_var(name=gblock_name, shape=[pnumel], dtype=pdtype)
        for new, (src, s, e, dtype) in slice_srcs.items():
            b.create_var(name=new, shape=[e - s], dtype=dtype, persistable=True)
        for n, v in local_tmp:
            b.create_var(
                name=n,
                shape=[int(d) for d in (v.shape if v is not None else [1])],
                dtype=(v.dtype if v is not None else "float32"),
            )
        for n in whole:
            v = src_block._find_var_recursive(n)
            b.create_var(
                name=n,
                shape=[int(d) for d in (v.shape if v is not None else [1])],
                dtype=(v.dtype if v is not None else "float32"),
                persistable=True,
            )

        for op in opt_ops:
            new_op = framework.Operator(b, op.type, None, None, dict(op.attrs))
            new_op.inputs = {
                slot: [rename[n] for n in names]
                for slot, names in op.inputs.items()
            }
            new_op.outputs = {
                slot: [rename[n] for n in names]
                for slot, names in op.outputs.items()
            }
            b.ops.append(new_op)
        return prog, gblock_name, slice_srcs, whole

    def get_pserver_program(self, endpoint):
        """Program with one listen_and_serv op for this endpoint."""
        if self.config.mode == "collective":
            return self._collective_pserver_program(endpoint)
        opt_by_param = {}
        for op in self.optimize_ops:
            rv = op.attrs.get("op_role_var")
            if rv:
                opt_by_param.setdefault(rv[0], []).append(op)

        shard_programs = []
        grad_to_shard = {}
        slice_plan = []
        whole_vars = set()
        for p, g in self.params_grads:
            for blk in self.param_blocks[p]:
                if self.block_eps[(p, blk.idx)] != endpoint:
                    continue
                ops = opt_by_param.get(p, [])
                assert len(ops) >= 1, "no optimizer op for param %s" % p
                prog, gblock_name, slice_srcs, whole = self._shard_program_for(
                    p, g, blk, ops
                )
                grad_to_shard[gblock_name] = len(shard_programs)
                shard_programs.append(prog)
                for new, (src, s, e, _dt) in slice_srcs.items():
                    slice_plan.append([src, new, s, e])
                whole_vars.update(whole)

        # lr decay ops run once per round on the pserver; their outputs are
        # marked persistable inside lr_program so the computed lr lands in
        # the server scope for the shard programs to read
        lr_program = None
        lr_produced = set()
        if self.lr_ops:
            lr_program = Program()
            lb = lr_program.global_block()
            src_block = self.origin_program.global_block()
            for op in self.lr_ops:
                lr_produced.update(op.output_arg_names())
            names = set()
            for op in self.lr_ops:
                names.update(op.input_arg_names())
                names.update(op.output_arg_names())
            for n in names:
                v = src_block._find_var_recursive(n)
                lb.create_var(
                    name=n,
                    shape=[int(d) for d in (v.shape if v is not None else [1])],
                    dtype=(v.dtype if v is not None else "float32"),
                    persistable=True,
                )
                # only pre-existing persistable inputs (step counters) need
                # the startup program to create them
                if n not in lr_produced and v is not None and v.persistable:
                    whole_vars.add(n)
            for op in self.lr_ops:
                new_op = framework.Operator(lb, op.type, None, None, dict(op.attrs))
                new_op.inputs = {k: list(v) for k, v in op.inputs.items()}
                new_op.outputs = {k: list(v) for k, v in op.outputs.items()}
                lb.ops.append(new_op)
        # vars the lr program computes are produced at runtime, not startup
        whole_vars -= lr_produced

        # this server's shard of each distributed lookup table:
        # [shard_var_name, source_table, server_idx, n_servers, lr_const,
        #  opt_spec] — opt_spec carries the optimizer type/hyperparams
        # captured from the table's dropped optimizer op
        server_idx = self.pserver_endpoints.index(endpoint)
        n_servers = len(self.pserver_endpoints)
        sparse_specs = [
            [info["shards"][server_idx], w, server_idx, n_servers,
             info["lr"], info.get("opt")]
            for w, info in sorted(getattr(self, "sparse_tables", {}).items())
        ]

        prog = Program()
        b = prog.global_block()
        b.append_op(
            "listen_and_serv",
            attrs={
                "endpoint": endpoint,
                "trainers": self.trainer_num,
                "sync_mode": bool(self.sync_mode),
                "optimize_programs": [sp.to_json() for sp in shard_programs],
                "lr_program": lr_program.to_json() if lr_program else None,
                "grad_to_shard": grad_to_shard,
                "slice_plan": slice_plan,
                "whole_vars": sorted(whole_vars),
                "sparse_tables": sparse_specs,
                # live pserver migration: the declarative plan spec lets
                # the SERVER re-derive shard->endpoint dispatch for a
                # changed pserver world and compute which of its shards
                # must move (None when the plan is not re-derivable —
                # migration then refuses, loudly, instead of guessing)
                "plan_spec": (self.plan_spec if self._plan_elastic
                              else None),
            },
        )
        return prog

    def get_elastic_pserver_program(self, endpoint):
        """Pserver program for an endpoint OUTSIDE the transpile-time set
        (elastic pserver grow, docs/FAULT_TOLERANCE.md "Live shard
        migration"): the server boots EMPTY — no shard programs, no
        slice plan, no sparse tables — and acquires state exclusively
        through journaled shard handoff (`migrate_in`).  It carries the
        plan spec so it can participate in world/commit handshakes, and
        the trainer/sync config so its round protocol matches the
        cluster it is joining."""
        if self.config.mode == "collective":
            raise ValueError(
                "elastic pserver programs are pserver-mode only (the "
                "collective hybrid pserver shards by a fixed table mod)")
        if endpoint in self.pserver_endpoints:
            raise ValueError(
                "%s is in the transpile-time pserver set — use "
                "get_pserver_program for base endpoints" % endpoint)
        if not getattr(self, "_plan_elastic", False):
            raise ValueError(
                "this job's comm plan is not runtime-re-derivable "
                "(custom dispatcher or legacy per-variable wire) — an "
                "elastic pserver could never be assigned shards")
        prog = Program()
        b = prog.global_block()
        b.append_op(
            "listen_and_serv",
            attrs={
                "endpoint": endpoint,
                "trainers": self.trainer_num,
                "sync_mode": bool(self.sync_mode),
                "optimize_programs": [],
                "lr_program": None,
                "grad_to_shard": {},
                "slice_plan": [],
                "whole_vars": [],
                "sparse_tables": [],
                "plan_spec": self.plan_spec,
                "elastic": True,
            },
        )
        return prog

    # ------------------------------------------------------------------
    def _collective_pserver_program(self, endpoint):
        """Hybrid collective pserver: SPARSE shards only.  Dense params
        never leave the mesh, so the program carries no optimize shard
        programs, no slice plan, and runs the service in per-arrival
        (async) application mode — there is no dense round whose barrier
        could trigger a merged apply.  Each mesh replica registers as its
        own logical trainer (rank = lax.axis_index), so `trainers` is the
        mesh size and the serve loop terminates when every replica
        completes."""
        if not getattr(self, "sparse_tables", None):
            raise ValueError(
                "collective mode has no pserver role for %s: the model "
                "has no distributed lookup tables, so every gradient "
                "rides the mesh — launch without pservers" % endpoint)
        server_idx = self.pserver_endpoints.index(endpoint)
        n_servers = len(self.pserver_endpoints)
        sparse_specs = [
            [info["shards"][server_idx], w, server_idx, n_servers,
             info["lr"], info.get("opt")]
            for w, info in sorted(self.sparse_tables.items())
        ]
        prog = Program()
        b = prog.global_block()
        b.append_op(
            "listen_and_serv",
            attrs={
                "endpoint": endpoint,
                "trainers": self.collective_nranks,
                "sync_mode": False,
                "optimize_programs": [],
                "lr_program": None,
                "grad_to_shard": {},
                "slice_plan": [],
                "whole_vars": [],
                "sparse_tables": sparse_specs,
            },
        )
        return prog

    # ------------------------------------------------------------------
    def get_startup_program(self, endpoint=None, pserver_program=None):
        """Pserver startup: run the ORIGINAL startup program (full shapes,
        same program structure + seed == bit-identical init with the
        trainers), then listen_and_serv slices this endpoint's blocks out
        of the resulting scope (slice_plan).  Reference analog:
        get_startup_program :853 re-runs initializers per shard."""
        return self.startup_program.clone()
