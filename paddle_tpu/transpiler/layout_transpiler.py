"""NHWC layout rewrite for convolution trunks.

Reference precedent: ``paddle/fluid/framework/data_layout_transform.*``
and the mkldnn placement passes (``ir/mkldnn_placement_pass`` family)
rewrite a program so layout-sensitive ops run in the library's preferred
layout, with layout transforms only at domain boundaries.  The TPU analog:
XLA tiles the minor-most dimension onto the 128-wide lane axis, so convs
whose channel dim is minor (NHWC) avoid the relayout/transpose traffic
that NCHW operands incur around every conv.  This pass converts every
conv/pool/BN/activation/residual-add trunk to NHWC:

- one ``transpose2`` where an NCHW var enters a conv,
- trunk ops propagate NHWC via their ``data_format``/``data_layout``
  attr (conv2d, depthwise_conv2d, pool2d, batch_norm) or are
  layout-agnostic (activations, dropout, cast, same-shape
  elementwise_add),
- one ``transpose2`` back to NCHW at each exit to a layout-sensitive
  consumer (reshape/fc/...), emitted lazily only where actually needed.

Run BEFORE ``optimizer.minimize`` (like ``rewrite_bf16``) so the grad ops
differentiate through the transposes; when combining with AMP, run this
pass first — the inserted transposes are dtype-transparent trunk ops for
the AMP propagation.

Caveats (documented in docs/MIGRATION.md): after the rewrite, trunk
intermediates are produced only as their ``@NHWC`` aliases; fetching one
of them by name from ``exe.run`` requires fetching the alias, listing it
in ``program._protected_fetch_names`` before the pass (those stay
materialized in NCHW, same contract as the fuse passes), or leaving
that var out of the trunk.  Vars read by sub-block ops are materialized
in NCHW automatically.  RNG-consuming trunk ops (dropout) keep their
distribution but not their exact stream — the inserted transposes shift
op indices, and the per-op RNG folds in the op position (give the op a
``seed`` attr for a layout-independent stream).
"""

from .. import framework
from ..core.trace import op_sub_blocks

NHWC_PERM = (0, 2, 3, 1)
NCHW_PERM = (0, 3, 1, 2)

# unary ops whose lowering is elementwise over X -> Out and therefore
# layout-agnostic (resnet trunks use relu; the rest ride along free)
_UNARY = ("relu", "relu6", "leaky_relu", "gelu", "sigmoid", "tanh", "sqrt", "abs")


def _permuted(shape):
    if shape and len(shape) == 4:
        return [shape[i] for i in NHWC_PERM]
    return list(shape) if shape else shape


def _names_read_in_subblocks(block):
    """Var names referenced by ops living in any sub-block of `block`'s
    ops — those must keep their NCHW materialization."""
    names = set()
    program = block.program

    def visit(b):
        for op in b.ops:
            for idx in op_sub_blocks(op):
                sub = program.block(idx)
                for sop in sub.ops:
                    names.update(sop.input_arg_names())
                    names.update(sop.output_arg_names())
                visit(sub)

    visit(block)
    return names


def rewrite_nhwc(program=None):
    """Rewrite (in place) the conv trunk of `program`'s global block to
    NHWC.  Returns the number of ops flipped to NHWC.  Must run before
    ``optimizer.minimize`` (and before ``rewrite_bf16`` when combining)."""
    program = program or framework.default_main_program()
    block = program.global_block()
    subblock_reads = _names_read_in_subblocks(block)

    new_ops = []
    nhwc = {}  # orig var name -> @NHWC alias name
    materialized = set()  # orig names also produced in NCHW
    count = 0

    def alias_for(name):
        """Create (once) the NHWC alias var of `name`."""
        if name in nhwc:
            return nhwc[name]
        v = block._find_var_recursive(name)
        alias = name + "@NHWC"
        block.create_var(
            name=alias,
            shape=_permuted(list(v.shape)) if v is not None and v.shape else None,
            dtype=str(v.dtype) if v is not None else "float32",
        )
        nhwc[name] = alias
        return alias

    def _transpose(src, dst, perm):
        op = framework.Operator(block, "transpose2", None, None, {"axis": list(perm)})
        op.inputs = {"X": [src]}
        op.outputs = {"Out": [dst]}
        new_ops.append(op)

    def to_nhwc(name):
        """NHWC view of `name`, inserting an entry transpose if needed."""
        if name in nhwc and _produced_nhwc.get(name):
            return nhwc[name]
        alias = alias_for(name)
        _transpose(name, alias, NHWC_PERM)
        _produced_nhwc[name] = True
        return alias

    def to_nchw(name):
        """Materialize the original NCHW `name` from its NHWC alias (once)."""
        if name not in nhwc or name in materialized:
            return
        _transpose(nhwc[name], name, NCHW_PERM)
        materialized.add(name)

    # whether the alias var has actually been written in the new op stream
    _produced_nhwc = {}

    def rewire_out(op, slot):
        out = op.outputs[slot][0]
        alias = alias_for(out)
        op.outputs[slot] = [alias]
        _produced_nhwc[out] = True
        return out

    def finish(op, out_name):
        new_ops.append(op)
        if out_name in subblock_reads:
            to_nchw(out_name)

    def var_shape(name):
        v = block._find_var_recursive(name)
        return list(v.shape) if v is not None and v.shape else None

    for op in list(block.ops):
        t = op.type
        if t in ("conv2d", "depthwise_conv2d") and op.attrs.get("data_format", "NCHW") == "NCHW":
            x = op.inputs["Input"][0]
            op.inputs["Input"] = [to_nhwc(x)]
            op.attrs["data_format"] = "NHWC"
            out = rewire_out(op, "Output")
            count += 1
            finish(op, out)
            continue
        if t == "pool2d" and op.inputs["X"][0] in nhwc and op.attrs.get("data_format", "NCHW") == "NCHW":
            op.inputs["X"] = [to_nhwc(op.inputs["X"][0])]
            op.attrs["data_format"] = "NHWC"
            out = rewire_out(op, "Out")
            count += 1
            finish(op, out)
            continue
        if t == "batch_norm" and op.inputs["X"][0] in nhwc:
            op.inputs["X"] = [to_nhwc(op.inputs["X"][0])]
            op.attrs["data_layout"] = "NHWC"
            out = rewire_out(op, "Y")
            count += 1
            finish(op, out)
            continue
        if t in _UNARY and op.inputs["X"][0] in nhwc:
            op.inputs["X"] = [to_nhwc(op.inputs["X"][0])]
            out = rewire_out(op, "Out")
            finish(op, out)
            continue
        if t == "cast" and op.inputs["X"][0] in nhwc:
            x = op.inputs["X"][0]
            op.inputs["X"] = [to_nhwc(x)]
            out = rewire_out(op, "Out")
            finish(op, out)
            continue
        if t == "dropout" and op.inputs["X"][0] in nhwc:
            op.inputs["X"] = [to_nhwc(op.inputs["X"][0])]
            out = rewire_out(op, "Out")
            if op.outputs.get("Mask"):
                rewire_out(op, "Mask")
            finish(op, out)
            continue
        if t == "elementwise_add":
            x, y = op.inputs["X"][0], op.inputs["Y"][0]
            if (
                (x in nhwc or y in nhwc)
                and op.attrs.get("axis", -1) in (-1, 0)
                and var_shape(x) == var_shape(y)
            ):
                op.inputs["X"] = [to_nhwc(x)]
                op.inputs["Y"] = [to_nhwc(y)]
                out = rewire_out(op, "Out")
                finish(op, out)
                continue
        # any other consumer needs the original NCHW materialization
        for name in op.input_arg_names():
            to_nchw(name)
        new_ops.append(op)

    # protected fetch targets (program._protected_fetch_names, same
    # contract as the fuse passes) must stay materialized in NCHW even
    # when every remaining consumer was rewired to the @NHWC alias
    for name in getattr(program, "_protected_fetch_names", ()):
        to_nchw(name)

    block.ops = new_ops
    return count
