"""Pipeline-parallel training: stage-sliced programs + microbatch schedules.

``pipeline_program(program, mesh, ...)`` slices a BUILT train program (fwd +
backward + optimizer ops already appended) into S stage sub-programs at
activation-frontier cut points — ``detect_segments`` waists generalized by
one extra live tensor, so pre-LN residual-stream layer boundaries qualify —
balanced by the same per-activation byte model remat's estimator uses, and
drives a GPipe or 1F1B microbatch schedule as one ``lax.scan`` inside
``shard_map`` over a dp×mp×pp mesh.

Slicing contract
----------------
- Only the FORWARD region (ops before the first backward/optimize/lrsched op)
  is sliced.  Stage gradients come from ``jax.vjp``/``jax.value_and_grad`` of
  the traced stage forward — numerically the same math as the program's
  backward ops, which backward.py itself lowers through ``jax.vjp`` of the
  forward rules.
- The program's OPTIMIZER ops are reused verbatim: each stage re-traces the
  adam (+lr-schedule) ops owning its params, with the AD gradients fed under
  each op's declared Grad input name.  ``TrainPartitionRules`` stage-scoped
  resolution (``StageResolution``) assigns every derived name — grads, Adam
  moments, beta-pow accumulators, bf16 cast mirrors — to its param's stage.
- Per-stage params + optimizer state pack into flat per-dtype buffers of
  shape [S, L] sharded ``P(pp)`` (the ``stack_stage_params`` discipline from
  parallel/pipeline.py lifted to ragged stages via per-stage layouts), so
  per-device state bytes are the max stage's, not the sum.
- Activations hop stage→stage over ``lax.ppermute``; heterogeneous stage
  boundaries ride one union carry dict (every boundary name, shapes fixed by
  ``jax.eval_shape``), and ``lax.switch`` on ``lax.axis_index(pp)`` picks the
  device's stage body.
- dp shards the batch (feeds split over dp; grads psum over dp); mp axes are
  carried through replicated within a stage in this revision.

Exactness: pp=1 returns the program untouched (bit-identical path); pp>=2
matches the unpipelined program at rtol<=1e-5 (same per-step RNG key, same
per-op fold-in indices — the keep-mask slice preserves op positions, and
dropout draws its mask over the full global batch rows via the
``microbatch_rows`` context so microbatching never changes the mask).

Schedules: "gpipe" runs all M forwards then one backward through the scanned
schedule (O(M) activation residency via the scan's stacked residuals);
"1f1b" interleaves, stashing at most 2S-1 in-flight stage inputs (O(S)
residency) and re-deriving each microbatch's backward with a per-tick
``jax.vjp``.
"""

import itertools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.registry import microbatch_rows
from ..core.trace import build_traced_function
from ..parallel.mesh import mesh_axis_sizes, pcast_varying, shard_map
from ..parallel.partition_rules import StageResolution, TrainPartitionRules
from .remat import (
    _activation_bytes,
    _is_activation,
    _op_reads,
    pin_rng_streams,
)

__all__ = [
    "PipelinePlan",
    "build_pipeline_plan",
    "pipeline_program",
    "pipeline_activation_report",
    "pipeline_state_report",
]

_BWD_ROLES = ("backward", "optimize", "lrsched", "rpc")
_SCHEDULES = ("gpipe", "1f1b")


# ---------------------------------------------------------------------------
# plan: the static slice of the program into stages
# ---------------------------------------------------------------------------
class PipelinePlan:
    """Static stage slicing of one train program.  Everything here is
    derivable from the program alone (no scope, no shapes beyond the
    batch_hint byte model), so the executor can build/verify against it
    and the verifier can diagnose it without running anything."""

    def __init__(self, **kw):
        self.__dict__.update(kw)

    def describe(self):
        lines = []
        for s, (lo, hi) in enumerate(self.stage_ranges):
            lines.append(
                "stage %d: ops[%d:%d) params=%d state_bytes=%d "
                "boundary_in=%s" % (
                    s, lo, hi, len(self.stage_params[s]),
                    self.state_bytes[s], self.boundary_in[s]))
        return "\n".join(lines)


def _forward_end(ops):
    for i, op in enumerate(ops):
        if op.attrs.get("op_role") in _BWD_ROLES:
            return i
    return len(ops)


def _find_loss_name(ops, fwd_end):
    """The backward seed op (backward.py: fill_constant of ones into
    <loss>@GRAD) names the loss."""
    for op in ops[fwd_end:]:
        if op.attrs.get("op_role") != "backward":
            continue
        if op.type != "fill_constant":
            continue
        outs = op.output_arg_names()
        if len(outs) == 1 and outs[0].endswith("@GRAD"):
            return outs[0][: -len("@GRAD")]
    return None


def _var_bytes(block, name, batch_hint):
    return _activation_bytes(block, name, batch_hint)


def _cut_candidates(program, block, fwd_end, max_frontier=2):
    """Forward op boundaries legal as stage cuts: the live activation
    frontier (non-persistable names defined before the boundary and read
    at/after it, within the forward region) holds at most `max_frontier`
    tensors.  ``detect_segments`` waists are exactly the frontier==1
    subset; admitting one more live tensor covers the pre-LN residual
    stream (residual + branch value), so transformer LAYER boundaries
    become cut points even though the residual keeps any single-tensor
    waist from forming there.  The union boundary carry hops every live
    name, so a multi-tensor cut costs hop bytes, not correctness."""
    ops = block.ops
    later_at = [set() for _ in range(fwd_end + 1)]
    for i in range(fwd_end - 1, -1, -1):
        later_at[i] = later_at[i + 1] | set(_op_reads(program, ops[i]))
    cuts = []
    defined = set()
    for b in range(1, fwd_end):
        defined.update(n for n in ops[b - 1].output_arg_names() if n)
        live = sum(1 for n in defined & later_at[b]
                   if _is_activation(block, n))
        if live <= max_frontier:
            cuts.append(b)
    return cuts


def _balance_stages(program, block, fwd_end, n_stages, batch_hint):
    """Partition the forward region into n_stages ranges over the legal
    cut points, minimizing the max per-stage activation bytes (primary —
    the estimator-balanced contract), tie-broken on max per-stage
    param+optimizer-state bytes (what bounds per-device HBM for the
    packed state buffers)."""
    ops = block.ops
    op_act = [0] * fwd_end
    op_state = [0] * fwd_end
    act_seen = set()
    params_seen = set()
    for i in range(fwd_end):
        for nm in ops[i].output_arg_names():
            if nm and nm not in act_seen and _is_activation(block, nm):
                act_seen.add(nm)
                op_act[i] += _var_bytes(block, nm, batch_hint)
        for nm in _op_reads(program, ops[i]):
            v = block._find_var_recursive(nm)
            if v is not None and v.persistable and nm not in params_seen:
                params_seen.add(nm)
                # param + two Adam moments (beta pows are scalars)
                op_state[i] += 3 * _var_bytes(block, nm, batch_hint)
    pa = [0] * (fwd_end + 1)
    ps = [0] * (fwd_end + 1)
    for i in range(fwd_end):
        pa[i + 1] = pa[i] + op_act[i]
        ps[i + 1] = ps[i] + op_state[i]

    cuts = _cut_candidates(program, block, fwd_end)
    if len(cuts) < n_stages - 1:
        raise ValueError(
            "program has only %d legal stage cut points (activation "
            "frontier <= 2) in its forward region — cannot slice into "
            "%d pipeline stages" % (len(cuts), n_stages))

    # keep enumeration tractable: drop the cut bordering the least
    # activation mass until the combination space is small
    while math.comb(len(cuts), n_stages - 1) > 100000:
        bounds = [0] + cuts + [fwd_end]
        k = min(range(1, len(bounds) - 1),
                key=lambda i: pa[bounds[i + 1]] - pa[bounds[i - 1]])
        del cuts[k - 1]

    best = None
    for comb in itertools.combinations(cuts, n_stages - 1):
        bounds = (0,) + comb + (fwd_end,)
        acts = [pa[b] - pa[a] for a, b in zip(bounds, bounds[1:])]
        states = [ps[b] - ps[a] for a, b in zip(bounds, bounds[1:])]
        key = (max(acts), max(states))
        if best is None or key < best[0]:
            best = (key, bounds)
    bounds = best[1]
    return [(a, b) for a, b in zip(bounds, bounds[1:])]


def build_pipeline_plan(program, n_stages, n_microbatches, schedule,
                        pp_axis="pp", dp_axis="dp", batch_hint=8,
                        stage_ranges=None):
    """Slice `program` into `n_stages` forward stages + per-stage optimizer
    slices.  `stage_ranges` overrides the balanced partition with explicit
    (lo, hi) forward op ranges — the verifier's mis-slice tests use this."""
    if schedule not in _SCHEDULES:
        raise ValueError("schedule must be one of %s, got %r"
                         % (_SCHEDULES, schedule))
    block = program.block(0)
    ops = block.ops
    n_ops = len(ops)
    fwd_end = _forward_end(ops)
    loss_name = _find_loss_name(ops, fwd_end)
    if loss_name is None:
        raise ValueError(
            "pipeline_program needs a built TRAIN program (append_backward "
            "ran): no loss-grad seed op found after op %d" % fwd_end)

    if stage_ranges is None:
        stage_ranges = _balance_stages(program, block, fwd_end,
                                       n_stages, batch_hint)
    else:
        stage_ranges = [tuple(r) for r in stage_ranges]

    # --- per-stage read/write sets over the forward region
    defined = []
    reads = []
    data_feeds = []
    fwd_persist = []
    for lo, hi in stage_ranges:
        d = set()
        r = set()
        dat = set()
        per = set()
        for op in ops[lo:hi]:
            for nm in _op_reads(program, op):
                if not nm:
                    continue
                v = block._find_var_recursive(nm)
                if v is None:
                    continue
                if v.persistable:
                    per.add(nm)
                elif getattr(v, "is_data", False):
                    dat.add(nm)
                else:
                    r.add(nm)
            for nm in op.output_arg_names():
                if nm:
                    d.add(nm)
        defined.append(d)
        reads.append(r)
        data_feeds.append(sorted(dat))
        fwd_persist.append(per)

    # params read by more than one forward stage cannot be stage-owned
    # (tied embeddings would need a grad cross-hop)
    owner = {}
    for s, per in enumerate(fwd_persist):
        for nm in per:
            if nm in owner and owner[nm] != s:
                raise NotImplementedError(
                    "param %r is read by pipeline stages %d and %d — "
                    "cross-stage weight sharing (tied embeddings) is not "
                    "supported; rebuild with tie_embeddings=False or "
                    "adjust the slicing" % (nm, owner[nm], s))
            owner.setdefault(nm, s)
    stage_params = [sorted(n for n, s in owner.items() if s == s_i)
                    for s_i in range(n_stages)]
    resolution = StageResolution(owner, n_stages)

    # --- boundary hops: what each stage must receive / forward along
    boundary_in = [sorted(r - d) for r, d in zip(reads, defined)]
    later_reads = [set() for _ in range(n_stages)]
    acc = set()
    for s in range(n_stages - 1, -1, -1):
        later_reads[s] = set(acc)
        acc |= reads[s]
    boundary_out = []
    avail = set()
    for s in range(n_stages):
        avail = (avail | defined[s])
        boundary_out.append(sorted(avail & later_reads[s]))
        avail = set(boundary_out[s])

    if loss_name not in defined[-1]:
        raise ValueError(
            "loss %r is not computed by the last pipeline stage (ranges "
            "%s) — the slicer must keep the loss head in stage S-1"
            % (loss_name, stage_ranges))

    stage_feed_names = []
    for s in range(n_stages):
        hop = boundary_out[s - 1] if s > 0 else []
        stage_feed_names.append(list(hop) + list(data_feeds[s]))

    # --- forward keep masks
    fwd_masks = []
    for lo, hi in stage_ranges:
        fwd_masks.append([lo <= i < hi for i in range(n_ops)])

    # --- optimizer region: assign each kept op to a stage (or all stages)
    opt_sets = [set() for _ in range(n_stages)]
    all_stage_ops = set()
    for i in range(fwd_end, n_ops):
        op = ops[i]
        role = op.attrs.get("op_role")
        if role == "backward":
            continue  # replaced by AD of the stage forward
        if role == "rpc":
            raise NotImplementedError(
                "pipeline_program cannot slice rpc ops (op %d)" % i)
        names = set(_op_reads(program, op)) | set(op.output_arg_names())
        stages = {resolution.stage_for(nm) for nm in names}
        stages.discard(None)
        if not stages or role == "lrsched":
            # pure lr-schedule / shared-state ops replicate into every
            # stage slice (each device steps its own copy of the shared
            # counters — identical values everywhere)
            all_stage_ops.add(i)
        elif len(stages) == 1:
            opt_sets[stages.pop()].add(i)
        else:
            raise NotImplementedError(
                "optimizer op %d (%s) touches params of stages %s — "
                "cross-stage optimizer ops (e.g. global-norm clip) are "
                "not supported under pipeline slicing"
                % (i, op.type, sorted(stages)))
    opt_masks = []
    for s in range(n_stages):
        kept = opt_sets[s] | all_stage_ops
        opt_masks.append([i in kept for i in range(n_ops)])

    # --- per-stage optimizer feeds: grad roots -> owning param
    grad_feed_param = []
    opt_persist = [set() for _ in range(n_stages)]
    shared_persist = set()
    for s in range(n_stages):
        kept = sorted(opt_sets[s] | all_stage_ops)
        written = set()
        for i in kept:
            written |= set(ops[i].output_arg_names())
        gmap = {}
        for i in kept:
            for nm in _op_reads(program, ops[i]):
                if not nm:
                    continue
                v = block._find_var_recursive(nm)
                if v is not None and v.persistable:
                    st = resolution.stage_for(nm)
                    if st == s:
                        opt_persist[s].add(nm)
                    elif st is None:
                        shared_persist.add(nm)
                    continue
                if nm in written:
                    continue
                base = resolution.base_name(nm)
                if base not in owner:
                    raise NotImplementedError(
                        "optimizer op %d reads %r, which is neither "
                        "produced by the stage-%d optimizer slice nor a "
                        "gradient of a stage-%d param" % (i, nm, s, s))
                gmap[nm] = base
            for nm in ops[i].output_arg_names():
                v = block._find_var_recursive(nm)
                if v is not None and v.persistable:
                    st = resolution.stage_for(nm)
                    if st == s:
                        opt_persist[s].add(nm)
                    elif st is None:
                        shared_persist.add(nm)
        grad_feed_param.append(gmap)

    stage_state_names = [
        sorted(set(stage_params[s]) | opt_persist[s])
        for s in range(n_stages)
    ]
    shared_state = sorted(
        shared_persist
        | {nm for per in fwd_persist for nm in per if nm not in owner})

    state_bytes = [
        sum(_var_bytes(block, nm, batch_hint) for nm in names)
        for names in stage_state_names
    ]
    act_bytes = []
    for s, (lo, hi) in enumerate(stage_ranges):
        seen = set()
        a = 0
        for op in ops[lo:hi]:
            for nm in op.output_arg_names():
                if nm and nm not in seen and _is_activation(block, nm):
                    seen.add(nm)
                    a += _var_bytes(block, nm, batch_hint)
        act_bytes.append(a)

    return PipelinePlan(
        n_stages=n_stages,
        pp_axis=pp_axis,
        dp_axis=dp_axis,
        schedule=schedule,
        n_microbatches=int(n_microbatches),
        batch_hint=batch_hint,
        fwd_end=fwd_end,
        loss_name=loss_name,
        stage_ranges=stage_ranges,
        fwd_masks=fwd_masks,
        opt_masks=opt_masks,
        stage_feed_names=stage_feed_names,
        data_feeds=data_feeds,
        boundary_in=boundary_in,
        boundary_out=boundary_out,
        stage_params=stage_params,
        stage_state_names=stage_state_names,
        shared_state=shared_state,
        grad_feed_param=grad_feed_param,
        resolution=resolution,
        state_bytes=state_bytes,
        act_bytes=act_bytes,
        last_defined=sorted(defined[-1]),
    )


def pipeline_program(program, mesh, pp_axis="pp", n_microbatches=None,
                     schedule="1f1b", batch_hint=8):
    """Stamp `program` for pipeline-parallel execution over `mesh`.

    With pp size 1 the program is returned UNTOUCHED (bit-identical
    single-program path).  Otherwise the plan is built (slicing validated),
    RNG streams are pinned (PR 12 discipline: op-position seeds survive any
    later rewrites), and ``program._pipeline`` carries {mesh, plan} for the
    executor's pp dispatch path.  `n_microbatches` defaults to the pp
    degree; the autotuner's ``n_microbatches`` knob (consult-only under
    FLAGS_program_autotune=0) feeds this argument."""
    sizes = mesh_axis_sizes(mesh)
    n_stages = int(sizes.get(pp_axis, 1))
    if n_stages == 1:
        return program
    pin_rng_streams(program)
    m = int(n_microbatches) if n_microbatches else n_stages
    if m < 1:
        raise ValueError("n_microbatches must be >= 1, got %d" % m)
    dp_axis = "dp" if "dp" in sizes else None
    plan = build_pipeline_plan(
        program, n_stages, m, schedule, pp_axis=pp_axis,
        dp_axis=dp_axis, batch_hint=batch_hint)
    program._pipeline = {"mesh": mesh, "plan": plan}
    return program


# ---------------------------------------------------------------------------
# reports: the estimator-backed numbers the bench + residency tests assert
# ---------------------------------------------------------------------------
def pipeline_activation_report(program, mb_rows=None):
    """Per-schedule peak activation residency from the remat byte model:
    GPipe stashes all M in-flight microbatches per stage, 1F1B at most
    min(M, 2(S-s)-1).  `mb_rows` is rows per microbatch (defaults to the
    plan's batch_hint)."""
    pp = getattr(program, "_pipeline", None)
    if pp is None:
        raise ValueError("program is not pipeline-stamped")
    plan = pp["plan"]
    block = program.block(0)
    rows = mb_rows if mb_rows is not None else plan.batch_hint
    S = plan.n_stages
    M = plan.n_microbatches
    out = {"n_stages": S, "n_microbatches": M, "mb_rows": rows}
    for sched in _SCHEDULES:
        per = []
        for s in range(S):
            names = plan.boundary_in[s] if s else plan.data_feeds[s]
            hop = sum(_activation_bytes(block, n, rows) for n in names)
            live = sum(
                _activation_bytes(block, n, rows)
                for n in _stage_act_names(program, plan, s))
            copies = M if sched == "gpipe" else min(M, 2 * (S - s) - 1)
            per.append(copies * (hop + live))
        out[sched] = {"per_stage": per, "peak_bytes": max(per)}
    return out


def _stage_act_names(program, plan, s):
    block = program.block(0)
    lo, hi = plan.stage_ranges[s]
    seen = []
    have = set()
    for op in block.ops[lo:hi]:
        for nm in op.output_arg_names():
            if nm and nm not in have and _is_activation(block, nm):
                have.add(nm)
                seen.append(nm)
    return seen


def pipeline_state_report(program):
    """Param+optimizer-state bytes: per-stage owned, shared (replicated),
    single-device total, and the per-device peak ratio the bench gates on
    (max stage + shared vs the whole program on one device)."""
    pp = getattr(program, "_pipeline", None)
    if pp is None:
        raise ValueError("program is not pipeline-stamped")
    plan = pp["plan"]
    block = program.block(0)
    per_stage = []
    for names in plan.stage_state_names:
        per_stage.append(
            sum(_var_bytes(block, n, plan.batch_hint) for n in names))
    shared = sum(
        _var_bytes(block, n, plan.batch_hint) for n in plan.shared_state)
    single = sum(per_stage) + shared
    peak = max(per_stage) + shared
    return {
        "per_stage_bytes": per_stage,
        "shared_bytes": shared,
        "single_device_bytes": single,
        "per_device_peak_bytes": peak,
        "peak_ratio": (float(peak) / single) if single else 0.0,
    }


# ---------------------------------------------------------------------------
# runtime: traced stage fns + packed state + the scheduled step
# ---------------------------------------------------------------------------
class PipelineRuntime:
    """One compiled pipeline step for one (program, feed signature,
    fetches).  Built by the executor's pp dispatch path on cache miss;
    holds the jitted step, the packed-state layout, and enough metadata
    to flush stage-owned state back into the scope."""

    def __init__(self, jitted, fetch_names, layouts, buffer_sharding,
                 shared_ro, shared_rw, feed_shardings, plan, mesh):
        self.jitted = jitted
        self.fetch_names = fetch_names
        self.layouts = layouts  # {dtype: [per-stage [(name, off, size, shape)]]}
        self.buffer_sharding = buffer_sharding
        self.shared_ro = shared_ro  # names
        self.shared_rw = shared_rw  # names
        self.feed_shardings = feed_shardings
        self.plan = plan
        self.mesh = mesh

    def buffer_names(self):
        return ["__pp_state_" + dt for dt in sorted(self.layouts)]

    def pack_state(self, scope):
        """Gather stage-owned persistables from the scope into the [S, L]
        per-dtype buffers, device_put sharded P(pp)."""
        out = {}
        S = self.plan.n_stages
        for dt in sorted(self.layouts):
            L = max(
                (ent[1] + ent[2] for per in self.layouts[dt] for ent in per),
                default=0)
            buf = np.zeros((S, L), dtype=dt)
            for s, per in enumerate(self.layouts[dt]):
                for name, off, size, _shape in per:
                    buf[s, off:off + size] = np.asarray(
                        scope.find_var(name), dtype=dt).reshape(-1)
            out["__pp_state_" + dt] = jax.device_put(
                buf, self.buffer_sharding)
        return out

    def unpack_state(self, buffers, scope):
        """Write stage-owned persistables from the packed buffers back to
        the scope (checkpointing / inspection path, not the hot loop)."""
        for dt in sorted(self.layouts):
            buf = np.asarray(buffers["__pp_state_" + dt])
            for s, per in enumerate(self.layouts[dt]):
                for name, off, size, shape in per:
                    scope.set(name, buf[s, off:off + size].reshape(shape))


def flush_pipeline_state(program, scope):
    """Copy stage-owned params/optimizer state from the packed pp buffers
    back into `scope` (the buffers are authoritative between flushes)."""
    entry = getattr(program, "_pipeline_runtime", None)
    if entry is None:
        return False
    entry["runtime"].unpack_state(entry["state"], scope)
    return True


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def build_pipeline_runtime(program, plan, mesh, scope, feed_arrays,
                           fetch_names):
    """Build the compiled pipeline step: per-stage traced fns, packed-state
    layouts, union-carry shapes, the schedule body, and the jit wrapper
    matching the executor's (feeds, ro_state, rw_state, rng_key) calling
    discipline."""
    S = plan.n_stages
    M = plan.n_microbatches
    pp_axis = plan.pp_axis
    sizes = mesh_axis_sizes(mesh)
    dp_axis = plan.dp_axis if plan.dp_axis in sizes else None
    dp = int(sizes.get(dp_axis, 1)) if dp_axis else 1
    repl = NamedSharding(mesh, P())

    # --- batch geometry ----------------------------------------------------
    data_names = sorted({n for per in plan.data_feeds for n in per})
    missing = [n for n in data_names if n not in feed_arrays]
    if missing:
        raise ValueError("pipeline program needs feeds %s" % missing)
    lead = {feed_arrays[n].shape[0] for n in data_names}
    if len(lead) != 1:
        raise ValueError(
            "pipeline data feeds disagree on batch dim: %s"
            % {n: feed_arrays[n].shape for n in data_names})
    b_global = lead.pop()
    if b_global % (dp * M) != 0:
        raise ValueError(
            "global batch %d must divide by dp*n_microbatches = %d*%d"
            % (b_global, dp, M))
    b_local = b_global // dp
    mb = b_local // M

    # --- traced stage forward + optimizer fns ------------------------------
    internal_fetch = [plan.loss_name] + [
        n for n in fetch_names if n != plan.loss_name]
    last_ok = set(plan.last_defined) | set(plan.stage_feed_names[-1])
    bad = [n for n in internal_fetch if n not in last_ok]
    if bad:
        raise NotImplementedError(
            "fetch targets %s are not produced by the last pipeline stage "
            "— only last-stage scalars (loss, counters) can be fetched "
            "under pipelining" % bad)

    stage_fetch = [list(plan.boundary_out[s]) for s in range(S - 1)]
    stage_fetch.append(internal_fetch)
    traced_fwd = []
    for s in range(S):
        t = build_traced_function(
            program, 0, plan.stage_feed_names[s], stage_fetch[s], scope,
            keep=plan.fwd_masks[s])
        if t.rw_names or t.updated:
            raise NotImplementedError(
                "pipeline stage %d forward writes persistable state %s "
                "(e.g. BN statistics) — not supported" % (s, t.updated))
        traced_fwd.append(t)

    grad_names = [sorted(plan.grad_feed_param[s]) for s in range(S)]
    traced_opt = [
        build_traced_function(
            program, 0, grad_names[s], (), scope, keep=plan.opt_masks[s])
        for s in range(S)
    ]
    shared_rw = sorted({
        n for t in traced_opt for n in t.updated if n in set(plan.shared_state)
    })
    shared_ro = sorted(
        {n
         for t in traced_fwd + traced_opt
         for n in t.ro_names
         if n in set(plan.shared_state)} - set(shared_rw))

    # --- packed state layouts ---------------------------------------------
    owned_vals = []
    for s in range(S):
        vals = {}
        for n in plan.stage_state_names[s]:
            vals[n] = np.asarray(scope.find_var(n))
        owned_vals.append(vals)
    dtypes = sorted({str(v.dtype) for vals in owned_vals for v in
                     vals.values()})
    layouts = {dt: [] for dt in dtypes}
    for dt in dtypes:
        for s in range(S):
            per = []
            off = 0
            for n in plan.stage_state_names[s]:
                v = owned_vals[s][n]
                if str(v.dtype) != dt:
                    continue
                per.append((n, off, int(v.size), tuple(v.shape)))
                off += int(v.size)
            layouts[dt].append(per)
    buffer_sharding = NamedSharding(mesh, P(pp_axis))
    stage_of_name = {}
    for s in range(S):
        for n in plan.stage_state_names[s]:
            stage_of_name[n] = s

    def unflatten(s, rows):
        out = {}
        for dt in dtypes:
            for name, off, size, shape in layouts[dt][s]:
                out[name] = rows[dt][off:off + size].reshape(shape)
        return out

    def reflatten(s, rows, updates):
        new = dict(rows)
        for dt in dtypes:
            r = new[dt]
            for name, off, size, shape in layouts[dt][s]:
                if name in updates:
                    r = r.at[off:off + size].set(
                        jnp.asarray(updates[name], r.dtype).reshape(-1))
            new[dt] = r
        return new

    # --- abstract union-carry shapes via eval_shape chain ------------------
    key_abs = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    data_abs = {
        n: jax.ShapeDtypeStruct((mb,) + feed_arrays[n].shape[1:],
                                feed_arrays[n].dtype)
        for n in data_names
    }
    union_specs = {}
    fetch_specs = {}
    for s in range(S):
        feeds_abs = {}
        for n in plan.stage_feed_names[s]:
            feeds_abs[n] = data_abs[n] if n in data_abs else union_specs[n]
        ro_abs = {}
        for n in traced_fwd[s].ro_names:
            v = (owned_vals[s].get(n)
                 if n in owned_vals[s] else scope.find_var(n))
            v = np.asarray(v)
            ro_abs[n] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        fetches_abs, _ = jax.eval_shape(
            traced_fwd[s].fn, feeds_abs, ro_abs, {}, key_abs)
        for n, a in zip(stage_fetch[s], fetches_abs):
            spec = jax.ShapeDtypeStruct(a.shape, a.dtype)
            if s < S - 1:
                union_specs[n] = spec
            else:
                fetch_specs[n] = spec
    union_names = sorted(union_specs)
    for n in union_names:
        if not jnp.issubdtype(union_specs[n].dtype, jnp.inexact):
            raise NotImplementedError(
                "stage boundary value %r has non-float dtype %s — the "
                "backward hop cannot carry its cotangent" %
                (n, union_specs[n].dtype))
    for n, spec in fetch_specs.items():
        if int(np.prod(spec.shape)) != 1:
            raise NotImplementedError(
                "fetch %r has shape %s — pipeline fetches must be scalars "
                "(losses, counters); fetch activations from an unpipelined "
                "clone instead" % (n, spec.shape))

    data_set = set(data_names)
    shared_ro_set = set(shared_ro)
    norm = float(M * dp)

    # --- per-stage switch branches ----------------------------------------
    def make_fwd_branch(s):
        def branch(rows, union, feeds_mb, sro, srw, key, row_offset):
            f = {}
            for n in plan.stage_feed_names[s]:
                f[n] = feeds_mb[n] if n in data_set else union[n]
            state = unflatten(s, rows)

            def look(n):
                if n in state:
                    return state[n]
                if n in shared_ro_set:
                    return sro[n]
                return srw[n]

            ro = {n: look(n) for n in traced_fwd[s].ro_names}
            with microbatch_rows(b_global, row_offset):
                fetches, _ = traced_fwd[s].fn(f, ro, {}, key)
            new_union = dict(union)
            if s < S - 1:
                for n, v in zip(stage_fetch[s], fetches):
                    new_union[n] = v
                loss = jnp.zeros((), jnp.float32)
                fvals = {n: jnp.zeros(fetch_specs[n].shape,
                                      fetch_specs[n].dtype)
                         for n in internal_fetch}
            else:
                got = dict(zip(stage_fetch[s], fetches))
                loss = _f32(got[plan.loss_name]).reshape(())
                fvals = {n: jnp.asarray(got[n], fetch_specs[n].dtype)
                         for n in internal_fetch}
            return new_union, loss, fvals

        return branch

    fwd_branches = [make_fwd_branch(s) for s in range(S)]

    def make_opt_branch(s):
        def branch(rows, grows, sro, srw, key):
            state = unflatten(s, rows)
            gfull = unflatten(s, grows)
            gfeeds = {g: jnp.asarray(gfull[p], state[p].dtype)
                      for g, p in plan.grad_feed_param[s].items()}
            ro = {}
            for n in traced_opt[s].ro_names:
                ro[n] = state[n] if n in state else (
                    sro[n] if n in shared_ro_set else srw[n])
            rw = {}
            for n in traced_opt[s].rw_names:
                rw[n] = state[n] if n in state else srw[n]
            _, new_state = traced_opt[s].fn(gfeeds, ro, rw, key)
            owned_new = {n: v for n, v in new_state.items()
                         if stage_of_name.get(n) == s}
            new_rows = reflatten(s, rows, owned_new)
            new_shared = {
                n: jnp.asarray(new_state.get(n, srw[n]),
                               jnp.asarray(srw[n]).dtype).reshape(
                                   jnp.asarray(srw[n]).shape)
                for n in shared_rw
            }
            return new_rows, new_shared

        return branch

    opt_branches = [make_opt_branch(s) for s in range(S)]

    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]

    def union_zero():
        return {
            n: pcast_varying(
                jnp.zeros(union_specs[n].shape, union_specs[n].dtype),
                (pp_axis,))
            for n in union_names
        }

    def fetch_zero():
        return {n: jnp.zeros(fetch_specs[n].shape, fetch_specs[n].dtype)
                for n in internal_fetch}

    def psum_all(x):
        x = jax.lax.psum(x, pp_axis)
        if dp_axis:
            x = jax.lax.psum(x, dp_axis)
        return x

    def device_step(feeds_local, sro, rw_local, key):
        s_idx = jax.lax.axis_index(pp_axis)
        dp_idx = jax.lax.axis_index(dp_axis) if dp_axis else 0
        rows = {dt: rw_local["__pp_state_" + dt][0] for dt in dtypes}
        srw = {n: rw_local[n] for n in shared_rw}
        feeds_resh = {
            n: feeds_local[n].reshape((M, mb) + feeds_local[n].shape[1:])
            for n in data_names
        }

        def feeds_at(m):
            return {
                n: jax.lax.dynamic_index_in_dim(a, m, 0, keepdims=False)
                for n, a in feeds_resh.items()
            }

        def run_stage(rows_, union, m):
            row_offset = dp_idx * b_local + m * mb
            return jax.lax.switch(
                s_idx, fwd_branches, rows_, union, feeds_at(m), sro, srw,
                key, row_offset)

        is_last = s_idx == S - 1

        if plan.schedule == "gpipe":
            def sched_loss(rows_):
                def tick(carry, t):
                    union, loss_acc, facc = carry
                    m_f = t - s_idx
                    m = jnp.clip(m_f, 0, M - 1)
                    new_union, loss_mb, fvals = run_stage(rows_, union, m)
                    emit = is_last & (m_f >= 0) & (m_f < M)
                    loss_acc = loss_acc + jnp.where(emit, loss_mb, 0.0)
                    facc = {
                        n: facc[n] + jnp.where(emit, _f32(fvals[n]),
                                               0.0).reshape(facc[n].shape)
                        for n in internal_fetch
                    }
                    sent = jax.tree_util.tree_map(
                        lambda v: jax.lax.ppermute(v, pp_axis, fwd_perm),
                        new_union)
                    return (sent, loss_acc, facc), None

                facc0 = {n: jnp.zeros((), jnp.float32)
                         for n in internal_fetch}
                init = (union_zero(), jnp.zeros((), jnp.float32), facc0)
                (_, loss_acc, facc), _ = jax.lax.scan(
                    tick, init, jnp.arange(M + S - 1))
                total = psum_all(loss_acc) / norm
                return total, facc

            (loss, facc), grows = jax.value_and_grad(
                sched_loss, has_aux=True)(rows)
        else:  # 1f1b
            buf_n = 2 * S - 1

            def tick(carry, t):
                union_f, ct_b, stash, loss_acc, facc, gacc = carry
                m_f = t - s_idx
                do_f = (m_f >= 0) & (m_f < M)
                mf = jnp.clip(m_f, 0, M - 1)
                m_b = t - (2 * S - 1) + s_idx
                do_b = (m_b >= 0) & (m_b < M)
                mbi = jnp.clip(m_b, 0, M - 1)
                slot_f = jnp.mod(mf, buf_n)
                slot_b = jnp.mod(mbi, buf_n)

                # read the stashed backward input BEFORE the forward
                # stash write lands in the same circular buffer
                x_res = jax.tree_util.tree_map(
                    lambda b: jax.lax.dynamic_index_in_dim(
                        b, slot_b, 0, keepdims=False), stash)

                new_union, loss_mb, fvals = run_stage(rows, union_f, mf)
                emit_f = is_last & do_f
                loss_acc = loss_acc + jnp.where(emit_f, loss_mb, 0.0)
                facc = {
                    n: facc[n] + jnp.where(emit_f, _f32(fvals[n]),
                                           0.0).reshape(facc[n].shape)
                    for n in internal_fetch
                }
                stash = jax.tree_util.tree_map(
                    lambda b, v: b.at[slot_f].set(
                        jnp.where(do_f, v, b[slot_f])),
                    stash, union_f)

                def fwd_for_vjp(rows_, union_in):
                    nu, lm, _ = run_stage(rows_, union_in, mbi)
                    return {n: nu[n] for n in union_names}, lm

                _, pull = jax.vjp(fwd_for_vjp, rows, x_res)
                ct_u = {
                    n: jnp.where(is_last, jnp.zeros_like(ct_b[n]), ct_b[n])
                    for n in union_names
                }
                ct_loss = jnp.where(
                    is_last & do_b, jnp.float32(1.0) / norm, 0.0)
                dr, du = pull((ct_u, ct_loss))
                gacc = jax.tree_util.tree_map(
                    lambda a, d: a + jnp.where(do_b, d, 0.0), gacc, dr)
                bwd_send = jax.tree_util.tree_map(
                    lambda d: jax.lax.ppermute(
                        jnp.where(do_b, d, 0.0), pp_axis, bwd_perm), du)
                fwd_send = jax.tree_util.tree_map(
                    lambda v: jax.lax.ppermute(v, pp_axis, fwd_perm),
                    new_union)
                return (fwd_send, bwd_send, stash, loss_acc, facc,
                        gacc), None

            stash0 = {
                n: jnp.zeros((buf_n,) + union_specs[n].shape,
                             union_specs[n].dtype)
                for n in union_names
            }
            gacc0 = {dt: jnp.zeros_like(rows[dt]) for dt in dtypes}
            facc0 = {n: jnp.zeros((), jnp.float32) for n in internal_fetch}
            init = (union_zero(), union_zero(), stash0,
                    jnp.zeros((), jnp.float32), facc0, gacc0)
            (_, _, _, loss_acc, facc, grows), _ = jax.lax.scan(
                tick, init, jnp.arange(M + 2 * S - 1))
            loss = psum_all(loss_acc) / norm

        if dp_axis:
            grows = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, dp_axis), grows)

        new_rows, new_shared = jax.lax.switch(
            s_idx, opt_branches, rows, grows, sro, srw, key)

        fetch_out = {}
        for n in internal_fetch:
            if n == plan.loss_name:
                fetch_out[n] = jnp.asarray(loss, fetch_specs[n].dtype
                                           ).reshape(fetch_specs[n].shape)
            else:
                v = psum_all(facc[n])
                fetch_out[n] = jnp.asarray(v, fetch_specs[n].dtype
                                           ).reshape(fetch_specs[n].shape)
        new_state = {"__pp_state_" + dt: new_rows[dt][None] for dt in dtypes}
        new_state.update(new_shared)
        return fetch_out, new_state

    # --- shard_map + jit wrapper ------------------------------------------
    def feed_spec(n):
        a = feed_arrays[n]
        if dp_axis and dp > 1 and a.ndim >= 1:
            return P(*((dp_axis,) + (None,) * (a.ndim - 1)))
        return P()

    feed_specs = {n: feed_spec(n) for n in data_names}
    rw_specs = {"__pp_state_" + dt: P(pp_axis) for dt in dtypes}
    rw_specs.update({n: P() for n in shared_rw})
    ro_specs = {n: P() for n in shared_ro}
    out_specs = ({n: P() for n in internal_fetch},
                 dict(rw_specs))

    def step_fn(feeds, ro_state, rw_state, rng_key):
        fetch_out, new_state = shard_map(
            device_step, mesh=mesh,
            in_specs=(feed_specs, ro_specs, dict(rw_specs), P()),
            out_specs=out_specs,
            check_rep=False,
        )(feeds, ro_state, rw_state, rng_key)
        return [fetch_out[n] for n in fetch_names], new_state

    feed_shardings = {n: NamedSharding(mesh, feed_specs[n])
                      for n in data_names}
    rw_shardings = {"__pp_state_" + dt: buffer_sharding for dt in dtypes}
    rw_shardings.update({n: repl for n in shared_rw})
    jitted = jax.jit(
        step_fn,
        in_shardings=(
            {n: feed_shardings[n] for n in data_names},
            {n: repl for n in shared_ro},
            rw_shardings,
            repl,
        ),
        out_shardings=(None, rw_shardings),
        donate_argnums=(2,),
    )
    return PipelineRuntime(
        jitted, list(fetch_names), layouts, buffer_sharding,
        shared_ro, shared_rw, feed_shardings, plan, mesh)
