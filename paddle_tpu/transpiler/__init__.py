"""Program -> Program transpilers (python/paddle/fluid/transpiler analog)."""

from .distribute_transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    slice_variable,
)
from .ps_dispatcher import HashName, RoundRobin, SizeWeighted
from .memory_optimization_transpiler import memory_optimize, release_memory
from .inference_transpiler import InferenceTranspiler
from .layout_transpiler import rewrite_nhwc
from . import fuse_passes  # noqa: F401  (registers the fusion-pass suite)
from . import remat  # noqa: F401  (registers remat_pass)
from .remat import detect_segments, remat_program
from .pipeline import (
    PipelinePlan,
    build_pipeline_plan,
    pipeline_activation_report,
    pipeline_program,
    pipeline_state_report,
)
from .autotune import tune as autotune_program
from .pass_registry import (
    OpPattern,
    Pass,
    apply_pass,
    get_pass,
    list_passes,
    register_pass,
)

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "slice_variable",
    "HashName",
    "RoundRobin",
    "SizeWeighted",
    "memory_optimize",
    "release_memory",
    "InferenceTranspiler",
    "detect_segments",
    "remat_program",
    "PipelinePlan",
    "build_pipeline_plan",
    "pipeline_activation_report",
    "pipeline_program",
    "pipeline_state_report",
    "autotune_program",
    "OpPattern",
    "Pass",
    "apply_pass",
    "get_pass",
    "list_passes",
    "register_pass",
]
