"""Inference-time program rewrites
(transpiler/inference_transpiler.py:24 analog), grown into a registry
pass PIPELINE (ROADMAP item 2a).

The reference folds conv+bn / conv+relu at the Python program level
before handing to the executor.  XLA already fuses elementwise chains
into the conv, so the transforms that still pay here are the
*algebraic* and *structural* ones, each registered as its own pass with
a numerical-parity contract:

* ``bn_fold_pass`` — fold batch_norm (inference form) into the
  preceding conv2d / depthwise_conv2d / fc / mul by rewriting the
  weights and bias in the scope, looking through an optional bias-add
  and an optional pure ``scale`` link (the BN/scale chain); output
  matches the unfused program at rtol 1e-5 and drops >= 1 op per folded
  BN (:70-300 analog).
* ``train_prune_pass`` — drop train-only ops: dropout rewrites to its
  is_test identity/scale form, and with a fetch cut
  (``program._protected_fetch_names``) everything below it — label
  slots, loss heads, metric accumulators — is sliced away; the kept
  fetches are value-identical.
* ``weight_int8_pass`` — weight-only int8 stamping
  (contrib.quantize.quantize_weights_int8, the serving engine's path,
  generalized): ANY program's mul/matmul/conv/embedding weights become
  int8+scale pairs dequantized at compute time.

``InferenceTranspiler.transpile`` runs the pipeline in that order; the
sub-passes are individually addressable through
``transpiler.apply_pass`` for custom pipelines.
"""

import numpy as np

# handlers for BN folding: op type -> (weight input slot, output slot,
# how a per-channel scale vector reshapes onto the weight)
_BN_FOLD_PRODUCERS = {
    "conv2d": ("Filter", "Output",
               lambda s, w: s.reshape((-1,) + (1,) * (w.ndim - 1))),
    "depthwise_conv2d": ("Filter", "Output",
                         lambda s, w: s.reshape((-1,) + (1,) * (w.ndim - 1))),
    # fc / mul: weight is [D_in, C_out] — the channel axis is LAST
    "fc": ("W", "Out", lambda s, w: s.reshape(1, -1)),
    "mul": ("Y", "Out", lambda s, w: s.reshape(1, -1)),
}


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None, fetches=None,
                  quantize_int8=False, int8_min_elems=1024):
        """Run the inference pass pipeline in place and return the
        program.

        fetches: optional fetch-target names (or Variables) defining the
        inference cut — ops below it (loss heads, label slots) are
        pruned; also recorded as ``_protected_fetch_names`` so later
        passes never fold a fetched value away.
        quantize_int8: finish with the weight-only int8 stamp."""
        from ..executor import global_scope

        scope = scope if scope is not None else global_scope()
        if fetches:
            names = tuple(
                f.name if hasattr(f, "name") else str(f) for f in fetches)
            existing = tuple(
                getattr(program, "_protected_fetch_names", ()) or ())
            program._protected_fetch_names = tuple(
                dict.fromkeys(existing + names))
            # prune FIRST: on a cloned train program the backward ops
            # still consume every forward intermediate, which would make
            # the BN fold's single-consumer checks refuse everything
            self._prune_to_fetches(program)
        self._fold_batch_norm(program, scope)
        self._drop_train_ops(program)
        if quantize_int8:
            from ..contrib.quantize import quantize_weights_int8

            quantize_weights_int8(program, scope=scope,
                                  min_elems=int8_min_elems)
        program._is_test = True
        program._bump_version()
        return program

    # ------------------------------------------------------------------
    def _drop_train_ops(self, program):
        block = program.global_block()
        consumers = self._consumer_count(block)
        new_ops = []
        alias = {}
        for op in block.ops:
            # rewrite inputs through accumulated aliases first
            for slot, names in op.inputs.items():
                op.inputs[slot] = [alias.get(n, n) for n in names]
            if op.type == "dropout":
                out, x = op.outputs["Out"][0], op.inputs["X"][0]
                impl = op.attrs.get("dropout_implementation", "downgrade_in_infer")
                if impl != "upscale_in_train":
                    # inference semantics = x * (1-p): keep as a scale op
                    # (which XLA fuses away) instead of an RNG mask
                    from .. import framework as _fw

                    sc = _fw.Operator(
                        block,
                        "scale",
                        None,
                        None,
                        {"scale": 1.0 - float(op.attrs.get("dropout_prob", 0.5))},
                    )
                    sc.inputs = {"X": [x]}
                    sc.outputs = {"Out": [out]}
                    new_ops.append(sc)
                    continue
                if consumers.get(x, 0) == 1 and new_ops:
                    # sole consumer: make the producer write the dropout's
                    # output name so fetches of `out` keep working
                    for prev in reversed(new_ops):
                        renamed = False
                        for slot, names in prev.outputs.items():
                            if x in names:
                                prev.outputs[slot] = [
                                    out if n == x else n for n in names
                                ]
                                renamed = True
                        if renamed:
                            break
                    else:
                        alias[out] = x
                else:
                    alias[out] = x
                continue
            new_ops.append(op)
        block.ops = new_ops

    # ------------------------------------------------------------------
    def _prune_to_fetches(self, program):
        """Slice the global block to the ancestor ops of the protected
        fetch names: the inference cut.  Label-slot processing, loss
        heads and metric ops below the cut disappear; unlike executor
        DCE this is a PROGRAM rewrite, so the saved/served artifact
        itself shrinks.  The slice is ``framework.backward_slice_keep``
        — the same walk behind ``Program._prune``, sub-block reads
        included."""
        from ..framework import backward_slice_keep

        targets = set(
            getattr(program, "_protected_fetch_names", ()) or ())
        if not targets:
            return 0
        block = program.global_block()
        keep = backward_slice_keep(program, targets)
        dropped = sum(1 for k in keep if not k)
        if dropped:
            block.ops = [op for i, op in enumerate(block.ops) if keep[i]]
            program._bump_version()
        return dropped

    # ------------------------------------------------------------------
    # producer/consumer maps come from the one shared def-use helper set
    # (analysis.graph) — the fold logic below keys off the SAME edges the
    # verifier and the fuse-pass matcher see
    def _producer_map(self, block):
        from ..analysis.graph import producer_map

        return producer_map(block)

    def _consumer_count(self, block):
        from ..analysis.graph import consumer_count

        return consumer_count(block)

    def _fold_batch_norm(self, program, scope):
        """producer (+ bias add) (+ pure scale) -> batch_norm  ==>
        producer with W' = W*s*g/std, b' = (b*s - mean)*g/std + beta.

        Producers: conv2d / depthwise_conv2d (per-out-channel, axis 0),
        fc / mul (per-out-column, last axis).  A trailing relu (the
        conv+BN+relu trunk form) is untouched by the fold and then
        eligible for conv_eltadd_relu/fuse_relu_into_conv.  The scale
        link must be a pure multiply (bias == 0).  Default CLOSED: any
        missing scope value, non-single-consumer link or unknown
        producer leaves the chain alone."""
        block = program.global_block()
        prod = self._producer_map(block)
        consumers = self._consumer_count(block)
        protected = set(
            getattr(program, "_protected_fetch_names", ()) or ())
        drop = set()

        for i, op in enumerate(block.ops):
            if op.type != "batch_norm":
                continue
            # inference-form BN only: a train-mode BN normalizes by
            # BATCH statistics (and updates the moving stats) — folding
            # the moving stats into the weights would silently change
            # the math.  clone(for_test=True) flips the attr.
            if not (op.attrs.get("is_test", False)
                    or getattr(program, "_is_test", False)):
                continue
            x = op.inputs["X"][0]
            if consumers.get(x, 0) != 1 or x not in prod:
                continue
            if x in protected:
                continue  # the fold deletes this name's definition
            cur = block.ops[prod[x]]
            s_factor = 1.0
            scale_op_idx = None
            if cur.type == "scale":
                # pure-scale link only: a bias would shift the BN input
                if float(cur.attrs.get("bias", 0.0)) != 0.0:
                    continue
                sx = cur.inputs["X"][0]
                if consumers.get(sx, 0) != 1 or sx not in prod:
                    continue
                if sx in protected:
                    continue  # its definition is rewired away below
                s_factor = float(cur.attrs.get("scale", 1.0))
                scale_op_idx = prod[x]
                cur = block.ops[prod[sx]]
            bias_add = None
            if cur.type == "elementwise_add":
                # producer -> elementwise_add(bias) -> [scale ->] bn (the
                # layer helper emits bias as a separate op)
                ax = cur.inputs["X"][0]
                if consumers.get(ax, 0) != 1 or ax not in prod:
                    continue
                if ax in protected:
                    continue  # its definition is rewired away below
                bias_add = cur
                cur = block.ops[prod[ax]]
            handler = _BN_FOLD_PRODUCERS.get(cur.type)
            if handler is None:
                continue
            w_slot, out_slot, reshape_scale = handler
            if cur.type == "fc" and cur.attrs.get("activation_type"):
                continue  # BN(act(xW+b)) has no affine fold
            if cur.type == "mul" and int(
                    cur.attrs.get("y_num_col_dims", 1)) != 1:
                continue
            if cur.type == "mul" and bias_add is None:
                # a bare mul has no Bias slot and no bias add to absorb
                # the shift — leave it (fc_fuse_pass normalizes the
                # common chains to fc, which folds)
                continue

            def val(slot):
                v = scope.find_var(op.inputs[slot][0])
                return None if v is None else np.array(v, dtype=np.float32)

            gamma, beta = val("Scale"), val("Bias")
            mean, var = val("Mean"), val("Variance")
            if any(v is None for v in (gamma, beta, mean, var)):
                continue
            eps = float(op.attrs.get("epsilon", 1e-5))
            std = np.sqrt(var + eps)

            wname = cur.inputs[w_slot][0]
            wvar = scope.find_var(wname)
            if wvar is None:
                continue
            w = np.array(wvar, dtype=np.float32)
            n_ch = (w.shape[0] if cur.type.endswith("conv2d")
                    else w.shape[-1])
            if gamma.shape != (n_ch,):
                continue  # channel mismatch: leave the defined chain alone

            # resolve the bias BEFORE any scope mutation: every abort
            # below this point would otherwise leave a half-folded chain
            # (weight rewritten, BN still in the program) that computes
            # silently wrong outputs — the default-CLOSED contract means
            # ALL-or-nothing
            new_bias_name = None
            if bias_add is not None and cur.inputs.get("Bias"):
                # TWO biases (the producer's own Bias slot plus a
                # separate add): folding only the add's operand would
                # leave the producer bias unscaled — refuse rather than
                # compute a silently wrong chain
                continue
            if bias_add is not None:
                bname = bias_add.inputs["Y"][0]
                bv = scope.find_var(bname)
                if bv is None:
                    continue
                b = np.array(bv, dtype=np.float32).reshape(-1)
            elif cur.inputs.get("Bias"):
                bname = cur.inputs["Bias"][0]
                bv = scope.find_var(bname)
                if bv is None:
                    continue
                b = np.array(bv, dtype=np.float32)
            else:
                bname = new_bias_name = wname + "@BN_FOLDED_BIAS"
                b = np.zeros(n_ch, dtype=np.float32)

            # all preconditions hold: mutate weight + bias together
            scope.set(wname, w * reshape_scale(
                np.asarray(s_factor * gamma / std, np.float32), w))
            if new_bias_name is not None:
                block.create_var(
                    name=new_bias_name, shape=[int(n_ch)],
                    dtype="float32", persistable=True,
                )
                cur.inputs["Bias"] = [new_bias_name]
            scope.set(bname, (b * s_factor - mean) * gamma / std + beta)

            # the op feeding bn now writes the bn output name directly
            tail = bias_add if bias_add is not None else cur
            t_slot = ("Out" if tail.type in ("elementwise_add", "fc", "mul")
                      else out_slot)
            tail.outputs[t_slot] = [op.outputs["Y"][0]]
            drop.add(i)
            if scale_op_idx is not None:
                drop.add(scale_op_idx)

        if drop:
            block.ops = [op for j, op in enumerate(block.ops) if j not in drop]
            program._bump_version()
