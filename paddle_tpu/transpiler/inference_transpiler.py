"""Inference-time program rewrites
(transpiler/inference_transpiler.py:24 analog).

The reference folds conv+bn / conv+relu at the Python program level before
handing to the executor.  XLA already fuses elementwise chains into the
conv, so the transforms that still pay here are the *algebraic* ones:

* fold batch_norm (inference form) into a preceding conv2d / fc / mul by
  rewriting the weights and bias in the scope (:70-300 analog);
* drop dropout ops (is_test identity) and other train-only ops.
"""

import numpy as np


class InferenceTranspiler:
    def transpile(self, program, place=None, scope=None):
        from ..executor import global_scope

        scope = scope if scope is not None else global_scope()
        self._fold_batch_norm(program, scope)
        self._drop_train_ops(program)
        program._is_test = True
        program._bump_version()
        return program

    # ------------------------------------------------------------------
    def _drop_train_ops(self, program):
        block = program.global_block()
        consumers = self._consumer_count(block)
        new_ops = []
        alias = {}
        for op in block.ops:
            # rewrite inputs through accumulated aliases first
            for slot, names in op.inputs.items():
                op.inputs[slot] = [alias.get(n, n) for n in names]
            if op.type == "dropout":
                out, x = op.outputs["Out"][0], op.inputs["X"][0]
                impl = op.attrs.get("dropout_implementation", "downgrade_in_infer")
                if impl != "upscale_in_train":
                    # inference semantics = x * (1-p): keep as a scale op
                    # (which XLA fuses away) instead of an RNG mask
                    from .. import framework as _fw

                    sc = _fw.Operator(
                        block,
                        "scale",
                        None,
                        None,
                        {"scale": 1.0 - float(op.attrs.get("dropout_prob", 0.5))},
                    )
                    sc.inputs = {"X": [x]}
                    sc.outputs = {"Out": [out]}
                    new_ops.append(sc)
                    continue
                if consumers.get(x, 0) == 1 and new_ops:
                    # sole consumer: make the producer write the dropout's
                    # output name so fetches of `out` keep working
                    for prev in reversed(new_ops):
                        renamed = False
                        for slot, names in prev.outputs.items():
                            if x in names:
                                prev.outputs[slot] = [
                                    out if n == x else n for n in names
                                ]
                                renamed = True
                        if renamed:
                            break
                    else:
                        alias[out] = x
                else:
                    alias[out] = x
                continue
            new_ops.append(op)
        block.ops = new_ops

    # ------------------------------------------------------------------
    def _producer_map(self, block):
        prod = {}
        for i, op in enumerate(block.ops):
            for n in op.output_arg_names():
                prod[n] = i
        return prod

    def _consumer_count(self, block):
        cnt = {}
        for op in block.ops:
            for n in op.input_arg_names():
                cnt[n] = cnt.get(n, 0) + 1
        return cnt

    def _fold_batch_norm(self, program, scope):
        """conv2d (no act) -> batch_norm  ==>  conv2d with W' = W*g/std,
        b' = (b-mean)*g/std + beta."""
        block = program.global_block()
        prod = self._producer_map(block)
        consumers = self._consumer_count(block)
        drop = set()

        for i, op in enumerate(block.ops):
            if op.type != "batch_norm":
                continue
            x = op.inputs["X"][0]
            if consumers.get(x, 0) != 1 or x not in prod:
                continue
            conv_idx = prod[x]
            conv = block.ops[conv_idx]
            bias_add = None
            if conv.type == "elementwise_add":
                # conv2d -> elementwise_add(bias) -> batch_norm chain (the
                # layer helper emits bias as a separate op)
                ax = conv.inputs["X"][0]
                if consumers.get(ax, 0) != 1 or ax not in prod:
                    continue
                bias_add = conv
                conv = block.ops[prod[ax]]
            if conv.type not in ("conv2d", "depthwise_conv2d"):
                continue

            def val(slot):
                v = scope.find_var(op.inputs[slot][0])
                return None if v is None else np.array(v, dtype=np.float32)

            gamma, beta = val("Scale"), val("Bias")
            mean, var = val("Mean"), val("Variance")
            if any(v is None for v in (gamma, beta, mean, var)):
                continue
            eps = float(op.attrs.get("epsilon", 1e-5))
            std = np.sqrt(var + eps)

            wname = conv.inputs["Filter"][0]
            wvar = scope.find_var(wname)
            if wvar is None:
                continue
            w = np.array(wvar, dtype=np.float32)
            scope.set(wname, w * (gamma / std).reshape(-1, 1, 1, 1))

            # fold the affine shift into the bias
            if bias_add is not None:
                bname = bias_add.inputs["Y"][0]
                b = np.array(scope.find_var(bname), dtype=np.float32).reshape(-1)
            elif conv.inputs.get("Bias"):
                bname = conv.inputs["Bias"][0]
                b = np.array(scope.find_var(bname), dtype=np.float32)
            else:
                bname = wname + "@BN_FOLDED_BIAS"
                block.create_var(
                    name=bname, shape=[int(w.shape[0])], dtype="float32",
                    persistable=True,
                )
                b = np.zeros(w.shape[0], dtype=np.float32)
                conv.inputs["Bias"] = [bname]
            scope.set(bname, (b - mean) * gamma / std + beta)

            # the op feeding bn now writes the bn output name directly
            tail = bias_add if bias_add is not None else conv
            out_slot = "Out" if tail.type == "elementwise_add" else "Output"
            tail.outputs[out_slot] = [op.outputs["Y"][0]]
            drop.add(i)

        if drop:
            block.ops = [op for j, op in enumerate(block.ops) if j not in drop]
