"""Parameter-block -> pserver placement policies
(python/paddle/fluid/transpiler/ps_dispatcher.py analog)."""


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """Blocks go to endpoints cyclically (the reference default)."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """Stable name-hash placement — rerunning a job maps blocks to the
    same servers (python-hash-free so it survives PYTHONHASHSEED)."""

    @staticmethod
    def _hash(s):
        h = 5381
        for ch in str(s):
            h = ((h * 33) ^ ord(ch)) & 0xFFFFFFFF
        return h

    @staticmethod
    def _key(v):
        # VarBlocks hash by their stable block name, never by repr (which
        # would bake a memory address into placement and desync the
        # trainers' plan from the pservers')
        return getattr(v, "block_name", v)

    def dispatch(self, varlist):
        return [self._eps[self._hash(self._key(v)) % len(self._eps)]
                for v in varlist]


class ConsistentHash(PSDispatcher):
    """Movement-minimizing hash-ring placement for ELASTIC worlds.

    SizeWeighted re-packs from scratch on every world change, shuffling
    shards between SURVIVING pservers (each shuffle is a live-migration
    handoff it never needed).  Here every endpoint owns VNODES points on
    a 32-bit ring (hashed with HashName's python-hash-free djb2, so the
    ring survives PYTHONHASHSEED and reruns); a block lands on the first
    vnode clockwise of its name hash.  Adding or removing an endpoint
    only reassigns the blocks whose arc that endpoint's vnodes cover —
    in expectation S/N shards move, and the 3->4->3 walk in
    tests/test_dist_transpiler.py pins moved <= ceil(S/N) per step.
    Selected like any dispatcher: flags={"split_method":
    "ConsistentHash"} through transpile/derive_plan."""

    VNODES = 64  # vnodes per endpoint: ring smoothness vs ring size

    @staticmethod
    def _point(s):
        # djb2 barely avalanches near-identical strings (endpoints
        # differ in one digit), which collapses every vnode cluster onto
        # one endpoint — a murmur3-style 32-bit finalizer spreads them
        h = HashName._hash(s)
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
        return h

    def __init__(self, pserver_endpoints):
        super().__init__(pserver_endpoints)
        ring = []
        for ep in self._eps:
            for v in range(self.VNODES):
                ring.append((self._point("%s#%d" % (ep, v)), ep))
        # ties (two vnodes, one hash) break by endpoint order: stable
        # across roles, independent of the eps list's ordering
        ring.sort()
        self._ring = ring

    def dispatch(self, varlist):
        import bisect

        keys = [h for h, _ in self._ring]
        out = []
        for v in varlist:
            h = self._point(HashName._key(v))
            i = bisect.bisect_right(keys, h) % len(self._ring)
            out.append(self._ring[i][1])
        return out


class SizeWeighted(PSDispatcher):
    """Greedy bin-pack by block size: each block lands on the currently
    least-loaded endpoint (stable tie-break = endpoint order), with load
    accumulated across dispatch() calls.  Position-based RoundRobin can
    pile every large block of a skewed model onto one server (k params
    each split across k servers stripe identically); weighting by size
    keeps per-server bytes — and therefore per-round optimize+transport
    work — balanced.  Deterministic for a fixed program, so every role
    replans the same placement."""

    def __init__(self, pserver_endpoints):
        super().__init__(pserver_endpoints)
        self._load = [0] * len(self._eps)

    def reset(self):
        super().reset()
        self._load = [0] * len(self._eps)

    def dispatch(self, varlist):
        out = []
        for v in varlist:
            size = int(getattr(v, "size", 1) or 1)
            i = min(range(len(self._eps)), key=lambda j: (self._load[j], j))
            self._load[i] += size
            out.append(self._eps[i])
        return out
