"""Parameter-block -> pserver placement policies
(python/paddle/fluid/transpiler/ps_dispatcher.py analog)."""


class PSDispatcher:
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    """Blocks go to endpoints cyclically (the reference default)."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    """Stable name-hash placement — rerunning a job maps blocks to the
    same servers (python-hash-free so it survives PYTHONHASHSEED)."""

    @staticmethod
    def _hash(s):
        h = 5381
        for ch in str(s):
            h = ((h * 33) ^ ord(ch)) & 0xFFFFFFFF
        return h

    def dispatch(self, varlist):
        return [self._eps[self._hash(v) % len(self._eps)] for v in varlist]
