"""Liveness-based memory planning
(transpiler/memory_optimization_transpiler.py analog: ControlFlowGraph :112,
memory_optimize :456, release_memory :494).

Under XLA the compiler owns buffer reuse inside a step, so the reference's
in-place var-rewrite becomes two things here:

1. the same liveness analysis over the Program, producing a reuse *plan*
   (which non-persistable vars can share storage) and an estimated HBM
   saving — kept for API parity, introspection and tests;
2. a donation set: vars whose last use precedes a persistable write can be
   donated to XLA (`jax.jit(donate_argnums=...)`) — recorded on the
   program as `_donate_vars` for the executor.
"""

import numpy as np


_DTYPE_SIZE = {
    "float32": 4,
    "float64": 8,
    "float16": 2,
    "bfloat16": 2,
    "int64": 8,
    "int32": 4,
    "int16": 2,
    "int8": 1,
    "uint8": 1,
    "bool": 1,
}


def _numel(shape):
    n = 1
    for d in shape or [1]:
        d = int(d)
        if d < 0:
            d = 1  # dynamic batch dim: count one row, report per-sample
        n *= d
    return n


class ControlFlowGraph:
    """Def/use + liveness over one block's op list.

    An op owning sub-blocks (while / cond / recompute / switch) USES
    everything its sub-blocks read from the outer scope: a var consumed
    only inside a nested block must stay live until that op runs, or the
    reuse plan would alias storage a loop body still reads."""

    def __init__(self, program, block_idx=0):
        from ..analysis.graph import def_use_lists

        self.program = program
        self.block = program.block(block_idx)
        self.ops = self.block.ops
        # the one shared def-use construction (analysis.graph): uses
        # include sub-block external reads, per the class contract above
        self.defs, self.uses = def_use_lists(program, block_idx)

    def live_ranges(self):
        """var -> (first def idx, last use idx)."""
        first_def = {}
        last_use = {}
        for i, op in enumerate(self.ops):
            for n in self.uses[i]:
                last_use[n] = i
            for n in self.defs[i]:
                first_def.setdefault(n, i)
                last_use[n] = max(last_use.get(n, i), i)
        return {
            n: (first_def[n], last_use.get(n, first_def[n])) for n in first_def
        }


def _var_bytes(block, name):
    v = block._find_var_recursive(name)
    if v is None:
        return 0, None
    size = _DTYPE_SIZE.get(str(v.dtype), 4)
    return _numel(v.shape) * size, v


def memory_optimize(input_program, skip_opt_set=None, print_log=False, level=0):
    """Compute the reuse plan + donation set for `input_program`.

    Returns {"reuse": {var: cache_var}, "saved_bytes": int}; also stored on
    the program (`_memory_opt_plan`, `_donate_vars`).
    """
    skip = set(skip_opt_set or ())
    block = input_program.global_block()
    cfg = ControlFlowGraph(input_program)
    ranges = cfg.live_ranges()

    def reusable(name):
        if name in skip:
            return False
        v = block._find_var_recursive(name)
        if v is None or v.persistable:
            return False
        if getattr(v, "is_data", False):
            return False
        return True

    def var_key(name):
        """(dtype, shape) aliasing identity.  The seed-era pool matched
        on BYTES alone, which let an int64 buffer alias a float32 one of
        equal numel (garbage bits reinterpreted) or a [4, 8] alias a
        [32] (any consumer relying on layout/strides breaks): aliasing
        is only sound between identically-typed, identically-shaped
        slots.  Refused candidates are counted loudly in the plan."""
        v = block._find_var_recursive(name)
        if v is None:
            return None
        return (str(v.dtype),
                tuple(int(d) for d in (v.shape or ())))

    # greedy first-fit reuse over a free pool, walking ops in order —
    # the reference's cache-pool algorithm (memory_optimize :456), but
    # keyed (dtype, shape), never numel
    reuse = {}
    saved = 0
    refused_mismatch = 0
    free_pool = []  # (name, bytes, (dtype, shape)) dead vars
    deaths = {}
    for name, (d, u) in ranges.items():
        deaths.setdefault(u, []).append(name)
    for i in range(len(cfg.ops)):
        for name in cfg.defs[i]:
            if not reusable(name) or name in reuse:
                continue
            nbytes, v = _var_bytes(block, name)
            if nbytes == 0:
                continue
            key = var_key(name)
            matched = False
            for j, (cand, cbytes, ckey) in enumerate(free_pool):
                if ckey == key and key is not None:
                    reuse[name] = cand
                    saved += nbytes
                    free_pool.pop(j)
                    matched = True
                    break
            if not matched and any(
                    cbytes >= nbytes and ckey != key
                    for _, cbytes, ckey in free_pool):
                # a seed-era bytes-only match existed: count the refusal
                refused_mismatch += 1
        for name in deaths.get(i, []):
            if reusable(name) and name not in reuse:
                nbytes, _ = _var_bytes(block, name)
                if nbytes:
                    free_pool.append((name, nbytes, var_key(name)))

    # defense in depth: no plan may ever pair mismatched vars — the
    # check is the verifier's alias-plan diagnostic (one implementation
    # shared with verify_program's consumers)
    from ..analysis.verifier import alias_plan_diagnostics

    bad = alias_plan_diagnostics(block, reuse)
    if bad:  # pragma: no cover
        raise AssertionError(
            "memory_optimize produced unsound aliases:\n  "
            + "\n  ".join(str(d) for d in bad))

    donate = sorted(
        n
        for n, (d, u) in ranges.items()
        if reusable(n) and u < len(cfg.ops) - 1 and n not in reuse
    )
    plan = {"reuse": reuse, "saved_bytes": saved,
            "refused_mismatch": refused_mismatch}
    if refused_mismatch and print_log:
        print(
            "memory_optimize: refused %d numel-compatible but "
            "dtype/shape-mismatched alias candidates (aliasing is only "
            "sound between identical slots)" % refused_mismatch
        )
    input_program._memory_opt_plan = plan
    input_program._donate_vars = donate
    if print_log:
        print(
            "memory_optimize: %d vars share storage, ~%.1f MB saved (XLA "
            "performs the in-step reuse; plan recorded)"
            % (len(reuse), saved / 1e6)
        )
    return plan


def release_memory(input_program, skip_opt_set=None):
    """Mark early-dying vars for eager release (release_memory :494).
    Under XLA this is the donation set; recorded on the program."""
    memory_optimize(input_program, skip_opt_set=skip_opt_set)
    return input_program._donate_vars
