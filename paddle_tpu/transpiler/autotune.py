"""TVM-style program autotuner (ROADMAP item 2c): search the discrete
PROGRAM knob space per (program-signature, shape-bucket), with a
persisted decision cache.

PR 11's ``ops/kernel_tuning.py`` made every pallas_call's block sizes a
searched, cached decision; this module lifts the same discipline one
level up, to knobs that select between whole PROGRAMS:

* ``mesh_shape``        — (dp, mp) or (dp, mp, pp) training mesh, None
                          = no mesh (a rebuild knob: the builder stamps
                          the candidate mesh via annotate_spmd + the
                          train rule table, and slices it with
                          ``pipeline_program`` when a pp extent > 1 is
                          present; shapes the visible device count
                          cannot host are never tried)
* ``rule_table``        — partition rules under a mesh: the registered
                          "family" table vs "replicated" (dp-only —
                          params stay replicated, the batch feeds still
                          shard); searched only once a mesh is in play
* ``bf16_amp``          — the bf16_amp_pass rewrite on/off (a rebuild
                          knob: AMP must precede minimize, so searching
                          it needs a ``variants`` builder callback)
* ``remat``             — checkpoint-segment count (rebuild knob, same
                          reason; FLAGS_hbm_budget_bytes forces it
                          outside the tuner when memory, not time, is
                          the binding constraint)
* ``prng_impl``         — threefry vs the hardware RBG stream for
                          dropout-heavy programs (flag knob)
* ``use_pallas``        — kernel-layer dispatch on/off; searched on a
                          real accelerator only (interpret-mode timings
                          are noise), and each timed candidate consults
                          the PR 11 kernel tuning cache for its block
                          sizes — the two cache layers compose
* ``steps_per_dispatch``— K steps per device dispatch via
                          Executor.run_loop's compiled lax.scan (the
                          host-dispatch-tax knob; applies to
                          steady-state fixed-feed stepping: bench legs,
                          eval loops — run() drivers with per-step data
                          keep 1)
* ``comm_bucket_bytes`` — consult-only: a distributed bench can deposit
                          a searched value, the tuner itself never
                          times multi-process candidates
* ``spec_k``            — consult-only serving knob: the speculative
                          chunk width a serve bench measured best for
                          this (model, shape) — acceptance rate is
                          workload-dependent, so the tuner never times
                          it on synthetic feeds; None = engine default
* ``use_draft``         — consult-only serving knob: arm the draft
                          model at all ("self" / True / False / None);
                          deposited by BENCH_SERVE_SPEC, never searched
* ``prefix_chunk``      — consult-only serving knob: prefix-cache match
                          granularity (a multiple of the engine width);
                          None = engine default (== width)
* ``n_microbatches``    — consult-only pipeline knob: the microbatch
                          count M a pipeline bench measured best for
                          this (model, shape) under a pp mesh; the
                          bubble fraction (S-1)/(M+S-1) vs per-tick
                          efficiency trade is batch- and
                          schedule-dependent, so the tuner never times
                          it on synthetic feeds; None = S (one
                          microbatch per stage)

Search is greedy coordinate descent (knob order as listed, best value
kept before moving on) bounded by ``max_trials`` timings; each timing
jits the candidate program on synthetic operands and measures
steady-state steps/s.  Decisions persist as JSON at
``FLAGS_program_tune_cache`` keyed (signature | feed shape-bucket |
device kind) with the exact bucketing discipline of
FLAGS_kernel_tune_cache (pow2 leading dims, exact feature dims), and
``FLAGS_program_autotune=0`` is the CI regime: consult-only, misses
return the all-defaults decision and never time anything.

Entry points: ``tune(program, feed_spec, ...)`` -> decision dict;
``tuned_flags(decision)`` -> the FLAGS_* mapping a driver applies.
"""

import hashlib
import threading
import time

import numpy as np

__all__ = [
    "DEFAULT_DECISION",
    "program_signature",
    "tune",
    "tuned_flags",
    "serving_knobs",
    "pipeline_knobs",
    "cache_stats",
    "clear_cache",
]

DEFAULT_DECISION = {
    "mesh_shape": None,          # (dp, mp) GSPMD mesh, None = no mesh
    "rule_table": "family",      # partition rules under a mesh:
    #                              "family" = the registered table,
    #                              "replicated" = params stay replicated
    #                              (dp-only sharding via the batch feeds)
    "bf16_amp": False,
    "remat": 0,
    "prng_impl": "threefry",
    "use_pallas": None,          # None = inherit FLAGS_use_pallas
    "steps_per_dispatch": 1,
    "comm_bucket_bytes": None,   # consult-only knob
    # consult-only SERVING knobs (ServingEngine fast path): deposited by
    # the serve bench, merged under cached decisions like every new knob
    # (a committed CI cache predating them keeps validating), and never
    # searched — acceptance rate and prefix locality are properties of
    # the TRAFFIC, which synthetic feeds cannot represent
    "spec_k": None,              # None = engine default (min(4, width))
    "use_draft": None,           # None = off; "self" | True = self-draft
    "prefix_chunk": None,        # None = engine default (== width)
    # consult-only PIPELINE knob (pp mesh legs): deposited by
    # BENCH_SPMD_PP, consumed via pipeline_knobs(decision)
    "n_microbatches": None,      # None = pipeline default (M == S)
}

# search order: rebuild knobs first (they change the op mix every later
# flag knob runs under) — the mesh before the rewrites that must compose
# with it — dispatch-schedule last
_KNOB_ORDER = ("mesh_shape", "rule_table", "bf16_amp", "remat",
               "prng_impl", "use_pallas", "steps_per_dispatch")

_lock = threading.RLock()
_cache = None
_cache_path = None
_stats = {"hits": 0, "misses": 0, "searches": 0, "search_ms": 0.0}


def _flag(name):
    from ..flags import get_flag

    return get_flag(name)


def program_signature(program):
    """Stable identity of a program's structure: the op type sequence of
    every block plus the persistable (name, shape, dtype) table, hashed.
    Deterministic across processes for the same build path (builders run
    under unique_name.guard), insensitive to feed VALUES — the shape
    side rides the cache key's shape bucket instead."""
    h = hashlib.sha1()
    for blk in program.blocks:
        for op in blk.ops:
            h.update(op.type.encode())
            h.update(b";")
        h.update(b"|")
    for name, v in sorted(program.global_block().vars.items()):
        if getattr(v, "persistable", False):
            h.update(("%s:%s:%s" % (name, v.shape, v.dtype)).encode())
    return h.hexdigest()[:16]


def _key(program, feed_spec):
    from ..ops.kernel_tuning import _device_kind, shape_bucket

    shapes = [shape for _, (shape, _dtype) in sorted(feed_spec.items())]
    return "|".join([program_signature(program), shape_bucket(shapes),
                     _device_kind()])


def _entry_valid(v):
    return isinstance(v.get("decision"), dict)


def _load_locked():
    global _cache, _cache_path
    from ..utils.tune_cache import load_entries

    path = str(_flag("program_tune_cache") or "")
    if _cache is not None and path == _cache_path:
        return
    _cache_path = path
    _cache = load_entries(path, _entry_valid, "program tuning cache")


def _save_locked():
    # searched decisions only, merged with concurrent writers, atomic
    # replace — the shared utils.tune_cache discipline kernel_tuning
    # established
    from ..utils.tune_cache import save_entries

    save_entries(_cache_path, _cache, _entry_valid,
                 "program tuning cache")


def _synthesize_feeds(feed_spec, seed=0):
    rng = np.random.RandomState(seed)
    feeds = {}
    for name, (shape, dtype) in feed_spec.items():
        dt = np.dtype(str(dtype)) if str(dtype) != "bfloat16" else None
        if dt is not None and dt.kind in "iu":
            # small ids stay legal for any lookup table
            feeds[name] = rng.randint(0, 2, size=shape).astype(dt)
        elif dt is not None and dt.kind == "b":
            feeds[name] = rng.rand(*shape) > 0.5
        else:
            feeds[name] = (rng.rand(*shape) * 0.1).astype(
                dt or np.float32)
    return feeds


def tuned_flags(decision):
    """The FLAGS_* mapping a driver applies before running the tuned
    program (flag knobs only; rebuild knobs are baked into the program
    the ``variants`` callback returned, and steps_per_dispatch is the
    driver's run()/run_loop() choice)."""
    out = {"prng_impl": decision.get("prng_impl", "threefry")}
    if decision.get("use_pallas") is not None:
        out["use_pallas"] = bool(decision["use_pallas"])
    return out


def serving_knobs(decision):
    """The ServingEngine keyword mapping for a decision's consult-only
    serving knobs — the serve-side twin of tuned_flags.  Only knobs the
    decision actually pins appear (None stays with the engine default),
    so ``ServingEngine(exe, hp, **serving_knobs(d), ...)`` composes with
    explicit call-site overrides."""
    out = {}
    if decision.get("spec_k") is not None:
        out["spec_k"] = int(decision["spec_k"])
    ud = decision.get("use_draft")
    if ud:  # "self" / True -> self-draft; False/None -> leave off
        out["draft"] = "self"
    if decision.get("prefix_chunk") is not None:
        out["prefix_chunk"] = int(decision["prefix_chunk"])
    return out


def pipeline_knobs(decision):
    """The ``pipeline_program`` keyword mapping for a decision's
    consult-only pipeline knobs — the pp-side twin of serving_knobs.
    Only knobs the decision pins appear (None stays with the pipeline
    default M == S), so ``pipeline_program(main, mesh,
    **pipeline_knobs(d))`` composes with explicit call-site
    overrides."""
    out = {}
    if decision.get("n_microbatches") is not None:
        out["n_microbatches"] = int(decision["n_microbatches"])
    return out


def _candidates_for(knob, rebuild, program, best=None):
    from .remat import detect_segments

    if knob == "mesh_shape":
        # rebuild knob: the builder stamps the program for the candidate
        # dp x mp mesh (annotate_spmd + train rules), or slices it with
        # pipeline_program for a (dp, mp, pp) triple — only shapes the
        # visible device count can host are tried.  Builders that
        # predate the pp axis raise on a 3-tuple; the search skips the
        # failed candidate (the _measure_decision exception path)
        if rebuild is None:
            return []
        import jax

        n = len(jax.devices())
        flat = [(dp, mp) for dp, mp in ((2, 1), (1, 2), (2, 2))
                if dp * mp <= n]
        pp3 = [(dp, mp, pp)
               for dp, mp, pp in ((1, 1, 2), (2, 1, 2), (1, 1, 4))
               if dp * mp * pp <= n]
        return flat + pp3
    if knob == "rule_table":
        # only meaningful once a mesh is in play: without one the table
        # never resolves, so the candidate would re-time the baseline
        if rebuild is None or not (best or {}).get("mesh_shape"):
            return []
        return ["family", "replicated"]
    if knob == "bf16_amp":
        return [False, True] if rebuild is not None else []
    if knob == "remat":
        if rebuild is None:
            return []
        n = max(0, len(detect_segments(program)) - 1)
        return [0, n] if n else []
    if knob == "prng_impl":
        return ["threefry", "rbg"]
    if knob == "use_pallas":
        from ..ops.pallas_kernels import _interpret

        return [] if _interpret() else [False, True]
    if knob == "steps_per_dispatch":
        return [1, 8]
    return []


def _measure_decision(decision, program, startup, feed_spec, fetches,
                      rebuild, steps, warmup, seed):
    """steps/s of one candidate: (re)build under the rebuild knobs, set
    the flag knobs, jit on synthetic operands, time steady state."""
    import jax

    from .. import executor as executor_mod
    from ..core import scope as scope_mod
    from ..flags import flag_items, set_flags
    from ..places import default_place

    main, startup_p, fetch_list = program, startup, fetches
    if rebuild is not None and (decision.get("bf16_amp")
                                or decision.get("remat")
                                or decision.get("mesh_shape")
                                or decision.get("rule_table",
                                                "family") != "family"):
        main, startup_p, fetch_list = rebuild(decision)
    saved = flag_items()
    set_flags(tuned_flags(decision))
    try:
        scope = scope_mod.Scope()
        with scope_mod.scope_guard(scope):
            exe = executor_mod.Executor(default_place())
            if startup_p is not None:
                startup_p.random_seed = 1234
                exe.run(startup_p, scope=scope)
            feeds = _synthesize_feeds(feed_spec, seed)
            window = int(decision.get("steps_per_dispatch", 1) or 1)
            if window > 1:
                out = exe.run_loop(window, main, feed=feeds,
                                   fetch_list=fetch_list,
                                   scope=scope, return_numpy=False)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                out = exe.run_loop(window, main, feed=feeds,
                                   fetch_list=fetch_list,
                                   scope=scope, return_numpy=False)
                jax.block_until_ready(out)
                return window / (time.perf_counter() - t0)
            out = None
            for _ in range(max(1, warmup)):  # >= 1: the first run is
                # the compile; timing it would measure XLA, not the step
                out = exe.run(main, feed=feeds, fetch_list=fetch_list,
                              scope=scope, return_numpy=False)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(steps):
                out = exe.run(main, feed=feeds, fetch_list=fetch_list,
                              scope=scope, return_numpy=False)
            jax.block_until_ready(out)
            return steps / (time.perf_counter() - t0)
    finally:
        set_flags({k: saved[k] for k in
                   ("prng_impl", "use_pallas") if k in saved})


def tune(program, feed_spec, startup=None, fetches=None, rebuild=None,
         max_trials=12, steps=4, warmup=2, measure=None, seed=0):
    """Return the tuned knob decision for (program, feed shapes).

    feed_spec: {name: (shape, dtype)} — ``utils.memory_analysis.
    program_feed_specs`` derives it from the program's data vars.
    startup/fetches: the program's startup twin and fetch list; needed
    to TIME candidates (a consult-only call can omit them).
    rebuild: optional callable(decision) -> (main, startup, fetches)
    re-running the model builder under the decision's REBUILD knobs
    (bf16_amp, remat) — those rewrites must precede minimize, so the
    builder is their natural owner; without it they are not searched.
    measure: optional decision -> steps/s callable injected by tests;
    with it the search runs regardless of FLAGS_program_autotune.

    Cache hit -> cached decision.  Miss -> greedy coordinate-descent
    search when allowed (FLAGS_program_autotune and a timeable setup),
    else the all-defaults decision; either way the decision is recorded
    (and persisted when FLAGS_program_tune_cache names a file) so it is
    made once per (program signature, shape bucket, device kind)."""
    with _lock:
        _load_locked()
        key = _key(program, feed_spec)
        hit = _cache.get(key)
        if hit is not None:
            _stats["hits"] += 1
            d = dict(DEFAULT_DECISION)
            d.update(hit["decision"])
            if isinstance(d.get("mesh_shape"), list):  # JSON round-trip
                d["mesh_shape"] = tuple(d["mesh_shape"])
            return d
        _stats["misses"] += 1

    can_search = measure is not None or (
        bool(_flag("program_autotune"))
        and startup is not None and fetches is not None)
    entry = {"decision": dict(DEFAULT_DECISION), "searched": False,
             "search_ms": 0.0}
    if can_search:
        if measure is None:
            def measure(decision):
                return _measure_decision(
                    decision, program, startup, feed_spec, fetches,
                    rebuild, steps, warmup, seed)

        t0 = time.perf_counter()
        best = dict(DEFAULT_DECISION)
        trials = 0
        try:
            best_sps = measure(dict(best))
            baseline_sps = best_sps
            trials += 1
            for knob in _KNOB_ORDER:
                if trials >= max_trials:
                    break
                for cand in _candidates_for(knob, rebuild, program, best):
                    if cand == best.get(knob) or (
                            knob == "use_pallas"
                            and best.get(knob) is None
                            and cand == bool(_flag("use_pallas"))):
                        continue  # already measured as part of `best`
                    if trials >= max_trials:
                        break
                    d = dict(best)
                    d[knob] = cand
                    try:
                        sps = measure(d)
                    except Exception as e:  # candidate failed: skip it
                        import sys

                        sys.stderr.write(
                            "autotune: candidate %s=%r failed (%r); "
                            "skipped\n" % (knob, cand, e))
                        continue
                    trials += 1
                    if sps > best_sps:
                        best, best_sps = d, sps
            ms = (time.perf_counter() - t0) * 1e3
            entry = {
                "decision": best,
                "searched": True,
                "search_ms": round(ms, 3),
                "trials": trials,
                "baseline_steps_per_s": round(float(baseline_sps), 4),
                "best_steps_per_s": round(float(best_sps), 4),
            }
        except Exception as e:
            import sys

            sys.stderr.write(
                "autotune: search failed (%r); seeding the all-defaults "
                "decision\n" % (e,))

    with _lock:
        _cache[key] = entry
        if entry["searched"]:
            _stats["searches"] += 1
            _stats["search_ms"] += entry["search_ms"]
            _save_locked()
    d = dict(DEFAULT_DECISION)
    d.update(entry["decision"])
    if isinstance(d.get("mesh_shape"), list):  # JSON round-trip
        d["mesh_shape"] = tuple(d["mesh_shape"])
    return d


def cache_stats():
    with _lock:
        _load_locked()
        return {
            "entries": len(_cache),
            "path": _cache_path,
            "searched": sum(1 for v in _cache.values()
                            if v.get("searched")),
            "stats": dict(_stats),
        }


def clear_cache(forget_path=False):
    """Drop the in-memory cache (tests); the on-disk file is untouched.
    forget_path also resets the load marker so the next consult reloads
    from FLAGS_program_tune_cache."""
    global _cache, _cache_path
    with _lock:
        _cache = None if forget_path else {}
        if forget_path:
            _cache_path = None
        _stats.update({"hits": 0, "misses": 0, "searches": 0,
                       "search_ms": 0.0})
