"""HBM-budgeted rematerialization pass (ROADMAP item 2b: the
BuddyAllocator analog for XLA-land).

``layers.recompute`` lets a model author mark a scope for activation
recomputation at BUILD time; this pass makes the same trade a
Program->Program decision: it detects layer boundaries in an
already-built forward program from the op graph alone, partitions the
program into checkpoint segments, and greedily marks segments for
recompute until the ``utils.memory_analysis`` peak-activation estimate
of the traced fwd+bwd fits an HBM budget
(``FLAGS_hbm_budget_bytes``).  Marked segments become ``recompute`` ops
(sub-block + ``jax.checkpoint`` lowering, ops/control_ops.py), so the
backward pass recomputes the segment's EXACT ops — random ops keep
their streams (moved ops are stamped with a ``seed`` attr reproducing
their original op-position RNG fold), losses are bit-identical to the
same partitioned program with checkpointing disabled
(policy="everything_saveable": identical vjp, nothing recomputed), the
forward pass is bit-identical to the unpartitioned original, and
training trajectories agree with it to float-roundoff (the
segment-level vjp may reassociate gradient fan-in sums by a ULP) —
recompute changes scheduling, never math.

Boundary detection: a layer boundary is a position in the op list where
the crossing activation frontier — non-persistable, non-data values
defined before and read at-or-after the position — hits a LOCAL minimum
(the transformer/bert/gpt2 residual stream, a resnet stage's single
activation; see ``detect_segments``).  Segments under ``min_ops`` merge
into their neighbor.  This finds transformer blocks and resnet stages
without model knowledge, which is what lets EVERY builder inherit the
pass.

Apply AFTER the fuse/AMP passes and BEFORE ``Optimizer.minimize``
(grads must differentiate through the recompute ops); the builders do
this when ``FLAGS_hbm_budget_bytes`` > 0.
"""

from .. import framework
from ..core.trace import op_sub_blocks, sub_block_external_reads
from .pass_registry import register_pass

__all__ = [
    "detect_segments",
    "pin_rng_streams",
    "remat_program",
    "wrap_segment",
]

# op types a checkpoint segment must never swallow: host/IO boundaries,
# control-flow whose sub-blocks carry their own env contract, and the
# rpc layer (side-effecting sends have no recompute semantics)
_UNWRAPPABLE = frozenset((
    "feed", "fetch", "read", "create_py_reader", "listen_and_serv",
    "while", "cond", "switch", "recompute",
))


def _op_reads(program, op):
    """All names an op reads, including its sub-blocks' external reads."""
    reads = list(op.input_arg_names())
    for sub_idx in op_sub_blocks(op):
        bound = op.attrs.get("__bound_names__", ())
        reads.extend(sub_block_external_reads(
            program, program.block(sub_idx), bound))
    return reads


def _is_activation(block, name):
    v = block._find_var_recursive(name)
    if v is None:
        return False
    return not v.persistable and not getattr(v, "is_data", False)


def _activation_bytes(block, name, batch_hint):
    import numpy as np

    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return 0
    n = 1
    for d in v.shape:
        n *= batch_hint if int(d) < 0 else int(d)
    dt = v.dtype or "float32"
    try:
        return n * np.dtype(str(dt)).itemsize
    except TypeError:
        return n * 2  # bfloat16


def detect_segments(program, block_idx=0, min_ops=3, op_range=None):
    """Partition the block's op list into layer-boundary segments.

    A boundary is a position where the crossing activation frontier (#
    of non-persistable, non-data values defined before and read at or
    after the position) is a LOCAL minimum — residual-block seams sit at
    narrow waists of the def-use graph, while long-lived mask/bias
    intermediates only raise the floor uniformly (which is why a global
    minimum rule fails: the floor differs between the encoder, decoder
    and loss-head regions).  Plateaus of equal width cut once, at their
    first position.  Segments shorter than min_ops merge into their
    successor.  Returns a list of (start, end) index pairs.

    `op_range`: optional (lo, hi) restricting detection to ops[lo:hi] —
    uses outside the window are ignored, so a forward-only window finds
    the forward graph's waists even though every activation also lives
    into the backward region (which would make the full frontier
    monotone).  Returned pairs are absolute op indices covering [lo, hi)."""
    block = program.block(block_idx)
    ops = block.ops
    base, hi = (0, len(ops)) if op_range is None else op_range
    base = max(0, base)
    hi = min(len(ops), hi)
    ops = ops[base:hi]
    n = len(ops)
    if n < 2 * min_ops:
        return [(base, base + n)]

    first_def = {}
    last_use = {}
    for i, op in enumerate(ops):
        for name in _op_reads(program, op):
            if name:
                last_use[name] = i
        for name in op.output_arg_names():
            if name:
                first_def.setdefault(name, i)
                last_use[name] = max(last_use.get(name, i), i)

    # frontier(p) = #names with first_def < p <= last_use, for p in
    # 1..n-1 — one linear difference-array pass, not per-position scans
    delta = [0] * (n + 2)
    for name in first_def:
        if not _is_activation(block, name):
            continue
        lo, hi = first_def[name] + 1, last_use[name]
        if lo <= hi:
            delta[lo] += 1
            delta[hi + 1] -= 1
    counts = []
    acc = 0
    for p in range(1, n):
        acc += delta[p]
        counts.append(acc)  # counts[i] = frontier at position i+1
    if not counts:
        return [(base, base + n)]

    # plateau-aware local minima: a maximal run of equal counts is a
    # boundary run when both neighbors are strictly higher; cut at the
    # run's first position
    cuts = []
    i = 0
    while i < len(counts):
        j = i
        while j + 1 < len(counts) and counts[j + 1] == counts[i]:
            j += 1
        left_higher = i == 0 or counts[i - 1] > counts[i]
        right_higher = j == len(counts) - 1 or counts[j + 1] > counts[i]
        if counts[i] > 0 and left_higher and right_higher and i > 0:
            cuts.append(i + 1)  # position index
        i = j + 1

    merged = []
    prev = 0
    for p in cuts:
        if p - prev >= min_ops:
            merged.append(p)
            prev = p
    if merged and n - merged[-1] < min_ops:
        merged.pop()
    bounds = [base + b for b in [0] + merged + [n]]
    return list(zip(bounds[:-1], bounds[1:]))


def _wrappable(program, ops_seg):
    from ..analysis.verifier import segment_diagnostics
    from ..core.registry import OPS

    for op in ops_seg:
        if op.type in _UNWRAPPABLE:
            return False
        opdef = OPS.get(op.type)
        if opdef is not None and getattr(opdef, "side_effect", False):
            return False
    # persistable-write + non-SSA-redefinition refusals are the
    # verifier's segment diagnostics (one implementation; the same
    # hazards verify_program reports when a recompute op already exists)
    return not segment_diagnostics(program, ops_seg)


def wrap_segment(program, ops_seg, protect=(), policy=None):
    """Move `ops_seg` (a contiguous run of global-block ops) into a new
    sub-block behind ONE `recompute` op at the run's position.

    inputs  = external reads (params included — the sub-block env is
              private, exactly like layers.recompute)
    outputs = segment-defined names read after the segment anywhere in
              the program, plus any `protect` names (fetch targets)

    Random ops keep their streams: a moved op with no explicit seed is
    stamped seed=<original (block<<20)|idx>, which reproduces the
    op-position RNG fold bit-for-bit (core/registry.LowerCtx.rng).
    Returns the created recompute Operator."""
    block = program.global_block()
    if not ops_seg:
        raise ValueError("empty segment")
    start = block.ops.index(ops_seg[0])
    for j, op in enumerate(ops_seg):
        if block.ops[start + j] is not op:
            raise ValueError("segment ops are not contiguous in the block")

    seg_set = set(id(op) for op in ops_seg)
    defined = set()
    in_names = []
    seen_in = set()
    for op in ops_seg:
        for name in _op_reads(program, op):
            if name and name not in defined and name not in seen_in:
                seen_in.add(name)
                in_names.append(name)
        for name in op.output_arg_names():
            if name:
                defined.add(name)

    used_after = set()
    for blk in program.blocks:
        for op in blk.ops:
            if id(op) in seg_set:
                continue
            for name in _op_reads(program, op):
                if name in defined:
                    used_after.add(name)
    for name in protect:
        if name in defined:
            used_after.add(name)
    out_names = sorted(used_after)
    if not out_names:
        raise ValueError(
            "segment exports nothing — wrapping it would disconnect the "
            "program (did you forget to protect the fetch targets?)")

    # RNG-stream parity for moved ops (see docstring)
    for j, op in enumerate(ops_seg):
        orig_idx = start + j  # (block 0 << 20) | idx
        if orig_idx > 0 and not int(op.attrs.get("seed", 0) or 0):
            op.attrs["seed"] = orig_idx

    saved_cur = program.current_block_idx
    sub = program.create_block(parent_idx=0)
    program.current_block_idx = saved_cur
    sub.ops = list(ops_seg)
    for op in ops_seg:
        op.block = sub

    rec = framework.Operator(
        block, "recompute", None, None,
        {
            "sub_block_idx": sub.idx,
            "in_names": list(in_names),
            "out_names": list(out_names),
            "__bound_names__": list(in_names),
            "remat_pass": True,
        },
    )
    if policy:
        rec.attrs["policy"] = str(policy)
    rec.inputs = {"X": list(in_names)}
    rec.outputs = {"Out": list(out_names)}
    del block.ops[start:start + len(ops_seg)]
    block.ops.insert(start, rec)
    program._bump_version()
    return rec


def pin_rng_streams(program, block_idx=0):
    """Stamp every op's RNG stream to its CURRENT op index via the
    ``seed`` attr (the fold ``LowerCtx.rng`` computes for seed=n is
    identical to the op-position fold for op_idx=n).

    Wrapping a segment replaces len(seg) ops with ONE recompute op, so
    every LATER op's position shifts — a dropout in an UNWRAPPED later
    layer would silently draw a different mask than the unremat
    program.  Pinning all streams to the pre-remat indices BEFORE any
    wrap keeps every random op's draw bit-identical regardless of how
    many segments end up marked.  (Known edge: op index 0 cannot be
    pinned — seed 0 means "unseeded" — but position 0 is a
    feed-adjacent op in every builder, never a random one, and it only
    moves if a segment starts at 0.)"""
    ops = program.block(block_idx).ops
    pinned = 0
    for idx, op in enumerate(ops):
        if idx > 0 and not int(op.attrs.get("seed", 0) or 0):
            op.attrs["seed"] = idx
            pinned += 1
    if pinned:
        program._bump_version()
    return pinned


def _segment_weight(program, seg_ops, batch_hint):
    block = program.global_block()
    return sum(
        _activation_bytes(block, name, batch_hint)
        for op in seg_ops
        for name in op.output_arg_names()
    )


def remat_program(program, budget_bytes, loss_name, feed_names=None,
                  batch_hint=8, policy=None, verbose=False):
    """Budgeted remat: mark the FEWEST segments (heaviest first) whose
    recompute brings the estimated fwd+bwd peak activation bytes under
    `budget_bytes`.  budget_bytes <= 0 means "mark everything" (the
    maximal-savings structural form).

    Call BEFORE minimize.  Returns the report dict also stamped on the
    program as ``_remat_report``:
    {before_bytes, after_bytes, budget_bytes, segments_total,
     segments_marked, fits}."""
    from ..utils import memory_analysis as ma

    block = program.global_block()
    if feed_names is None:
        feed_names = [v.name for v in block.vars.values()
                      if getattr(v, "is_data", False)]
    feed_specs = ma.program_feed_specs(program, feed_names, batch_hint)

    def estimate(prog):
        return ma.estimate_peak_activation_bytes(
            prog, feed_specs, loss_name)["peak_bytes"]

    protect = set([loss_name])
    protect.update(getattr(program, "_protected_fetch_names", ()) or ())

    segments = detect_segments(program)
    # last segment produces the loss head; never wrap it (its recompute
    # would save nothing — the loss is the output) and skip unwrappables
    candidates = []
    for (a, b) in segments[:-1]:
        seg_ops = block.ops[a:b]
        if seg_ops and _wrappable(program, seg_ops):
            candidates.append(seg_ops)
    candidates.sort(
        key=lambda seg: -_segment_weight(program, seg, batch_hint))

    before = estimate(program)
    report = {
        "before_bytes": int(before),
        "after_bytes": int(before),
        "budget_bytes": int(budget_bytes),
        "segments_total": len(segments),
        "segments_marked": 0,
        "fits": bool(before <= budget_bytes) if budget_bytes > 0
        else True,
    }
    if (budget_bytes > 0 and before <= budget_bytes) or not candidates:
        program._remat_report = report
        return report

    # pin EVERY op's RNG stream to its pre-remat index before any wrap:
    # a partial marking shifts the positions of later UNWRAPPED ops, and
    # an unpinned dropout there would draw a different mask than the
    # unremat program (wrap_segment pins the moved ops; this pins the
    # rest)
    pin_rng_streams(program)

    def marked_estimate(k):
        """Estimated peak with the k heaviest candidates wrapped, on a
        throwaway clone (op object identity maps by position)."""
        clone = program.clone()
        cblock = clone.global_block()
        idx_runs = []
        for seg in candidates[:k]:
            a = block.ops.index(seg[0])
            idx_runs.append((a, len(seg)))
        # wrap from the highest position down so earlier indices hold
        for a, ln in sorted(idx_runs, reverse=True):
            wrap_segment(clone, cblock.ops[a:a + ln], protect=protect,
                         policy=policy)
        return estimate(clone)

    # monotone in k: binary search the smallest k that fits; if even
    # k=all misses the budget, mark all (closest achievable)
    lo, hi = 1, len(candidates)
    best_k, best_est = hi, marked_estimate(hi)
    if budget_bytes > 0 and best_est <= budget_bytes:
        while lo < hi:
            mid = (lo + hi) // 2
            est = marked_estimate(mid)
            if est <= budget_bytes:
                hi = mid
                best_k, best_est = mid, est
            else:
                lo = mid + 1
        best_k = hi
    # apply for real, highest position first
    chosen = candidates[:best_k]
    runs = sorted(
        ((block.ops.index(seg[0]), seg) for seg in chosen), reverse=True)
    for _, seg in runs:
        wrap_segment(program, seg, protect=protect, policy=policy)
    after = estimate(program)
    report.update(
        after_bytes=int(after),
        segments_marked=best_k,
        # budget <= 0 is the documented mark-everything mode: there is
        # no budget to miss, so the result reads as success
        fits=bool(after <= budget_bytes if budget_bytes > 0 else True),
    )
    if verbose or not report["fits"]:
        import sys

        sys.stderr.write(
            "remat: peak activation %.2f MB -> %.2f MB (budget %.2f MB, "
            "%d/%d segments recomputed)%s\n" % (
                before / 1e6, after / 1e6, budget_bytes / 1e6, best_k,
                len(segments),
                "" if report["fits"] else " — BUDGET NOT MET (every "
                "wrappable segment already recomputes)"))
    program._remat_report = report
    return report


def maybe_remat(program, loss, is_test=False, batch_hint=8, mesh=None):
    """Builder hook: budgeted remat under FLAGS_hbm_budget_bytes.

    Called by the model builders between the fuse/AMP passes and
    ``minimize`` — a no-op unless the flag is set (> 0 bytes), so the
    default build is untouched.  Returns the remat report or None.

    The flag is a PER-DEVICE budget.  Under a GSPMD mesh the estimator
    still sees the global (unsharded) program, but the partitioner
    splits activations across the mesh — dp shards every row dim, mp
    shards the ffn/vocab column dims — so the global estimate maps to
    roughly budget x n_devices.  Scaling the budget (instead of the
    estimate) keeps the report's before/after numbers in global terms,
    comparable across mesh shapes."""
    from ..flags import get_flag

    budget = int(get_flag("hbm_budget_bytes"))
    if is_test or budget <= 0:
        return None
    n_shards = 1
    if mesh is not None:
        for s in mesh.devices.shape:
            n_shards *= int(s)
    name = loss.name if hasattr(loss, "name") else str(loss)
    report = remat_program(program, budget * n_shards, name,
                           batch_hint=batch_hint)
    report["per_device_budget_bytes"] = budget
    report["mesh_shards"] = n_shards
    return report


@register_pass("remat_pass")
def _remat_pass(program, scope):
    """Registry form: mark EVERY wrappable detected segment for
    recompute (the maximal-savings structural rewrite; no estimator).
    For the budgeted form call ``remat_program`` directly — the model
    builders do, under FLAGS_hbm_budget_bytes."""
    block = program.global_block()
    protect = set(getattr(program, "_protected_fetch_names", ()) or ())
    segments = detect_segments(program)
    pin_rng_streams(program)
    marked = 0
    for (a, b) in reversed(segments[:-1]):
        seg_ops = block.ops[a:b]
        if seg_ops and _wrappable(program, seg_ops):
            try:
                wrap_segment(program, seg_ops, protect=protect)
                marked += 1
            except ValueError:
                continue
    program._remat_marked_count = marked
    return program
