"""Optimizers (python/paddle/fluid/optimizer.py analog).

``Optimizer.minimize`` (optimizer.py:294 parity) = append_backward +
regularization + gradient clip + per-parameter optimizer ops
(_create_optimization_pass :197).  The emitted ops compile into the same XLA
executable as forward/backward, so the whole training step is one fused TPU
program.
"""

import numpy as np

from . import framework, unique_name
from .backward import append_backward
from .framework import Variable
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = [
    "SGD",
    "Momentum",
    "LarsMomentum",
    "Adagrad",
    "Adam",
    "Adamax",
    "DecayedAdagrad",
    "Adadelta",
    "RMSProp",
    "Ftrl",
    "ModelAverage",
    "GradientMergeOptimizer",
    "SGDOptimizer",
    "MomentumOptimizer",
    "LarsMomentumOptimizer",
    "AdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "DecayedAdagradOptimizer",
    "AdadeltaOptimizer",
    "RMSPropOptimizer",
    "FtrlOptimizer",
    "Optimizer",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        self._accumulators = {}  # name -> {param_name: var}
        self.helper = None
        self.type = self.__class__.__name__.lower()

    # ---- learning rate ---------------------------------------------------
    def _create_global_learning_rate(self):
        program = framework.default_main_program()
        lr = self._learning_rate_map.get(program, None)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[program] = self._learning_rate
            return
        from .layers import tensor

        lr_var = tensor.create_global_var(
            name=unique_name.generate("learning_rate"),
            shape=[1],
            value=float(self._learning_rate),
            dtype="float32",
            persistable=True,
        )
        self._learning_rate_map[program] = lr_var

    def _global_learning_rate(self, program=None):
        if program is None:
            program = framework.default_main_program()
        return self._learning_rate_map.get(program)

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if isinstance(param_lr, Variable):
            # a scheduler wrote a per-param LR variable (append_LARS):
            # use it directly (optimizer.py reference behavior)
            return param_lr
        base = self._global_learning_rate()
        if param_lr == 1.0:
            return base
        from .layers import nn

        return nn.scale(base, scale=float(param_lr))

    # ---- accumulators ----------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        block = framework.default_main_program().global_block()
        shape = list(shape or param.shape)
        var = block.create_var(
            name=unique_name.generate(param.name + "_" + name),
            shape=shape,
            dtype=dtype or param.dtype,
            persistable=True,
            stop_gradient=True,
        )
        sb = framework.default_startup_program().global_block()
        sv = sb.create_var(name=var.name, shape=shape, dtype=var.dtype, persistable=True)
        Constant(float(fill_value))(sv, sb)
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # ---- driver ----------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads):
        program = framework.default_main_program()
        block = program.global_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(block, [p for p, g in parameters_and_grads if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                with program._optimized_guard(list(param_and_grad)):
                    optimize_ops.append(
                        self._append_optimize_op(block, param_and_grad)
                    )
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        from . import regularizer as _reg
        from . import clip as _clip

        params_grads = _clip.append_gradient_clip_ops(params_grads)
        params_grads = _reg.append_regularization_ops(params_grads, self.regularization)
        return self._create_optimization_pass(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "sgd",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param]},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "momentum",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(
        self, learning_rate, momentum=0.9, lars_coeff=0.001, lars_weight_decay=0.0005, **kwargs
    ):
        super().__init__(learning_rate, **kwargs)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "lars_momentum",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "VelocityOut": [velocity]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "adagrad",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kwargs
    ):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            "adam",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param],
                "Moment1Out": [m1],
                "Moment2Out": [m2],
                "Beta1PowOut": [b1p],
                "Beta2PowOut": [b2p],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "adamax",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [self._get_accumulator("moment", param)],
                "InfNorm": [self._get_accumulator("inf_norm", param)],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", param)],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param],
                "MomentOut": [self._get_accumulator("moment", param)],
                "InfNormOut": [self._get_accumulator("inf_norm", param)],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, parameters_and_grads):
        # advance beta1^t per param
        for param, grad in parameters_and_grads:
            if grad is None:
                continue
            b1p = self._get_accumulator("beta1_pow_acc", param)
            block.append_op(
                "scale",
                inputs={"X": [b1p]},
                outputs={"Out": [b1p]},
                attrs={"scale": self._beta1},
            )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "decayed_adagrad",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator("_avg_squared_grad", param)
        asu = self._get_accumulator("_avg_squared_update", param)
        return block.append_op(
            "adadelta",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "AvgSquaredGrad": [asg],
                "AvgSquaredUpdate": [asu],
            },
            outputs={
                "ParamOut": [param],
                "AvgSquaredGradOut": [asg],
                "AvgSquaredUpdateOut": [asu],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        **kwargs
    ):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "rmsprop",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "Moment": [self._get_accumulator("momentum", param)],
                "MeanSquare": [self._get_accumulator("mean_square", param)],
                "MeanGrad": [self._get_accumulator("mean_grad", param)],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param],
                "MomentOut": [self._get_accumulator("momentum", param)],
                "MeanSquareOut": [self._get_accumulator("mean_square", param)],
                "MeanGradOut": [self._get_accumulator("mean_grad", param)],
            },
            attrs={
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "ftrl",
            inputs={
                "Param": [param],
                "Grad": [grad],
                "SquaredAccumulator": [self._get_accumulator("squared", param)],
                "LinearAccumulator": [self._get_accumulator("linear", param)],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param],
                "SquaredAccumOut": [self._get_accumulator("squared", param)],
                "LinearAccumOut": [self._get_accumulator("linear", param)],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


SGD = SGDOptimizer
Momentum = MomentumOptimizer
LarsMomentum = LarsMomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer


class ModelAverage(Optimizer):
    """Parameter averaging for evaluation (optimizer.py:1365 ModelAverage).

    Accumulates a running sum of every trainable parameter after each
    step (one fused `model_average_accum` op per param — the TPU
    re-expression of the reference's sum_1/2/3 rotation: the window
    restarts once num_updates exceeds max_average_window); `apply()`
    swaps params for their windowed average, `restore()` puts the
    trained values back.

        opt.minimize(loss)
        model_average = fluid.optimizer.ModelAverage(0.15)
        ...train...
        with model_average.apply(exe):
            ...evaluate with averaged weights...
    """

    def __init__(
        self,
        average_window_rate,
        min_average_window=10000,
        max_average_window=10000,
        regularization=None,
        name=None,
    ):
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._accums = {}  # param name -> (sum var, num var)
        main = framework.default_main_program()
        block = main.global_block()
        with main._op_role_guard("optimize"):
            for param in block.all_parameters():
                if not param.trainable:
                    continue
                helper = LayerHelper("model_average")
                psum = helper.create_global_variable(
                    name=unique_name.generate(param.name + "_avg_sum"),
                    persistable=True,
                    dtype=param.dtype,
                    shape=param.shape,
                )
                num = helper.create_global_variable(
                    name=unique_name.generate(param.name + "_avg_num"),
                    persistable=True,
                    dtype="float32",
                    shape=[1],
                )
                from .initializer import Constant

                num_upd = helper.create_global_variable(
                    name=unique_name.generate(param.name + "_avg_nupd"),
                    persistable=True,
                    dtype="float32",
                    shape=[1],
                )
                helper.set_variable_initializer(psum, Constant(0.0))
                helper.set_variable_initializer(num, Constant(0.0))
                helper.set_variable_initializer(num_upd, Constant(0.0))
                block.append_op(
                    "model_average_accum",
                    inputs={
                        "Param": [param],
                        "Sum": [psum],
                        "Num": [num],
                        "NumUpdates": [num_upd],
                    },
                    outputs={
                        "SumOut": [psum],
                        "NumOut": [num],
                        "NumUpdatesOut": [num_upd],
                    },
                    attrs={
                        "average_window_rate": float(average_window_rate),
                        "min_average_window": int(min_average_window),
                        "max_average_window": int(max_average_window),
                    },
                )
                self._accums[param.name] = (psum, num)

    def apply(self, executor, need_restore=True):
        """Context manager: params := sum/num inside, restored after."""
        import contextlib

        from .core.scope import global_scope

        outer = self

        @contextlib.contextmanager
        def ctx():
            scope = global_scope()
            backup = {}
            for pname, (psum, num) in outer._accums.items():
                backup[pname] = np.array(scope.get(pname))
                s = np.asarray(scope.get(psum.name))
                n = float(np.asarray(scope.get(num.name)).reshape(-1)[0])
                if n > 0:
                    scope.set(pname, (s / n).astype(backup[pname].dtype))
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in backup.items():
                        scope.set(pname, val)

        return ctx()

    def restore(self, executor):
        """No-op when apply() restored on exit (reference API parity)."""




class GradientMergeOptimizer:
    """Gradient accumulation over k micro-batches (the capability of the
    reference's ir/multi_batch_merge_pass, re-designed compile-first).

    Where the reference rewrites the graph into N forward/backward copies
    per step, here `minimize` splits training into TWO compiled programs
    with static shapes and no data-dependent control flow:

      * the MAIN program accumulates grads into persistable buffers
        (`<param>@GRAD@MERGED`) each `exe.run(main)` — no weight update;
      * `apply_program` applies the inner optimizer on the averaged
        buffers and zeroes them — run it every k-th micro-batch.

        opt = fluid.optimizer.GradientMergeOptimizer(
            fluid.optimizer.Adam(1e-3), k_steps=4)
        apply_prog = opt.minimize(loss)
        exe.run(fluid.default_startup_program())
        for i, batch in enumerate(batches):
            exe.run(feed=batch, fetch_list=[loss])
            if (i + 1) % 4 == 0:
                exe.run(apply_prog)

    Gradient clip / regularization configured on the inner optimizer
    apply at merge time (on the averaged grad), matching the reference's
    once-per-merged-batch semantics.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self.inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        self.apply_program = None

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .initializer import Constant
        from .layers import nn as _nn

        main = framework.default_main_program()
        startup = startup_program or framework.default_startup_program()
        block = main.global_block()
        params_grads = self.inner.backward(
            loss, startup, parameter_list, no_grad_set)

        merged = []  # (param, acc var)
        with main._op_role_guard("optimize"):
            for param, grad in params_grads:
                if grad is None or not param.trainable:
                    continue
                acc = block.create_var(
                    name=param.name + "@GRAD@MERGED",
                    shape=list(param.shape),
                    dtype=param.dtype,
                    persistable=True,
                    stop_gradient=True,
                )
                sb = startup.global_block()
                sv = sb.create_var(name=acc.name, shape=list(param.shape),
                                   dtype=param.dtype, persistable=True)
                Constant(0.0)(sv, sb)
                # acc += grad, in place on the persistable name
                block.append_op(
                    "elementwise_add",
                    inputs={"X": [acc.name], "Y": [grad.name]},
                    outputs={"Out": [acc.name]},
                    attrs={"axis": -1},
                )
                merged.append((param, acc))

        # the apply program: shares the scope by NAME with main
        apply_prog = framework.Program()
        with framework.program_guard(apply_prog, startup):
            ablock = apply_prog.global_block()
            pg = []
            for param, acc in merged:
                p2 = framework.Parameter(
                    ablock, list(param.shape), param.dtype, name=param.name)
                p2.trainable = True
                p2.optimize_attr = param.optimize_attr
                # per-param decay/clip must survive into merge-time
                # apply_gradients (regularizer.py / clip.py read these)
                p2.regularizer = param.regularizer
                p2.gradient_clip_attr = param.gradient_clip_attr
                ablock.vars[param.name] = p2
                a2 = ablock.create_var(
                    name=acc.name, shape=list(param.shape), dtype=param.dtype,
                    persistable=True, stop_gradient=True)
                g = (
                    _nn.scale(a2, scale=1.0 / self.k_steps)
                    if self.avg and self.k_steps > 1 else a2
                )
                pg.append((p2, g))
            self.inner.apply_gradients(pg)
            # zero the buffers for the next merge window
            with apply_prog._op_role_guard("optimize"):
                for param, acc in merged:
                    ablock.append_op(
                        "fill_constant",
                        inputs={},
                        outputs={"Out": [acc.name]},
                        attrs={"shape": list(acc.shape),
                               "dtype": param.dtype, "value": 0.0},
                    )
        self.apply_program = apply_prog
        return apply_prog
