"""Autodiff over the IR (python/paddle/fluid/backward.py analog).

``append_backward(loss)`` (backward.py:469 parity) walks the block's ops in
reverse, emitting one ``<type>_grad`` op per forward op and ``sum`` ops for
fan-in gradient accumulation (_addup_repetitive_outputs_ analog).  Unlike
the reference — where each op type ships a hand-written GradOpDescMaker and
grad kernels — grad ops here carry bookkeeping attrs and are lowered
generically through ``jax.vjp`` of the forward lowering (core/registry.py),
so gradient correctness is inherited from the forward rule.
"""

import numpy as np

from . import framework, unique_name
from .framework import Parameter, Variable, grad_var_name

__all__ = ["append_backward", "calc_gradient"]

_FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")


def _is_float_var(block, name):
    v = block._find_var_recursive(name)
    if v is None or v.dtype not in _FLOAT_DTYPES:
        return False
    # tensor arrays are opaque (TensorArray pytrees at trace time) — grads
    # don't flow through them (use the `recurrent` op for trainable loops)
    return getattr(v, "type", None) != framework.VarType.LOD_TENSOR_ARRAY


def _create_grad_var(block, ref_name, grad_name):
    ref = block._find_var_recursive(ref_name)
    return block.create_var(
        name=grad_name,
        shape=ref.shape if ref is not None else None,
        dtype=ref.dtype if ref is not None else "float32",
        persistable=False,
        stop_gradient=True,
    )


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Append grad ops for `loss` to its program; return [(param, grad)]."""
    program = loss.block.program
    block = program.global_block()
    no_grad = set(no_grad_set or ())
    with program._op_role_guard("backward"):
        return _append_backward_impl(
            loss, program, block, no_grad, parameter_list
        )


def _append_backward_impl(loss, program, block, no_grad, parameter_list):

    ops = block.ops
    n_fwd = len(ops)  # snapshot: ops appended below must not join the walk
    # backward slice: which ops are on the path to loss
    needed = {loss.name}
    on_path = [False] * n_fwd
    for i in range(n_fwd - 1, -1, -1):
        op = ops[i]
        if op.type.endswith("_grad"):
            continue
        if any(n in needed for n in op.output_arg_names()):
            on_path[i] = True
            needed.update(op.input_arg_names())

    # grad contributions: var -> [grad var names]
    contribs = {}
    finalized = {}

    def finalize(name):
        """Materialize the single accumulated grad var for `name`."""
        if name in finalized:
            return finalized[name]
        c = contribs.get(name, [])
        if not c:
            return None
        if len(c) == 1:
            finalized[name] = c[0]
            return c[0]
        gname = grad_var_name(name)
        if gname in [x for x in c]:
            gname = unique_name.generate(gname + "_acc")
        _create_grad_var(block, name, gname)
        block.append_op("sum", inputs={"X": list(c)}, outputs={"Out": [gname]})
        finalized[name] = gname
        return gname

    # seed: d loss / d loss = 1
    loss_grad = grad_var_name(loss.name)
    _create_grad_var(block, loss.name, loss_grad)
    block.append_op(
        "fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={
            "shape": list(loss.shape) if loss.shape else [1],
            "dtype": loss.dtype,
            "value": 1.0,
        },
    )
    contribs[loss.name] = [loss_grad]
    finalized[loss.name] = loss_grad

    for i in range(n_fwd - 1, -1, -1):
        if not on_path[i]:
            continue
        op = ops[i]
        # finalize grads of this op's outputs
        out_grads = {}  # slot -> [grad names or None]
        any_grad = False
        for slot, names in op.outputs.items():
            gs = []
            for n in names:
                g = finalize(n)
                gs.append(g)
                if g is not None:
                    any_grad = True
            out_grads[slot] = gs
        if not any_grad:
            continue
        if op.type == "while":
            raise RuntimeError(
                "gradients cannot flow through an unbounded While "
                "(lax.while_loop is not reverse-differentiable); construct "
                "it as layers.While(cond, max_iters=N) to lower to a "
                "differentiable masked scan, or use StaticRNN/DynamicRNN"
            )

        # build grad op inputs: forward inputs + out-grads
        gin = {}
        for slot, names in op.inputs.items():
            gin[slot] = list(names)
        for slot, names in op.outputs.items():
            gs = out_grads[slot]
            if all(g is None for g in gs):
                continue
            filled = []
            for n, g in zip(names, gs):
                if g is None:
                    # zero-fill missing output grads so slot lists align
                    zname = unique_name.generate(grad_var_name(n) + "_zero")
                    _create_grad_var(block, n, zname)
                    block.append_op(
                        "fill_zeros_like",
                        inputs={"X": [n]},
                        outputs={"Out": [zname]},
                    )
                    filled.append(zname)
                else:
                    filled.append(g)
            gin[slot + "@GRAD"] = filled

        # outputs: grads of differentiable float inputs (slots the op's
        # registry entry marks no-grad — e.g. lookup_table Ids, optimizer
        # state — never get grad vars, matching what lower_grad_op produces)
        from .core.registry import OPS

        opdef = OPS.get(op.type)
        no_grad_slots = opdef.no_grad_inputs if opdef else set()
        gout = {}
        for slot, names in op.inputs.items():
            if slot in no_grad_slots:
                continue
            outs = []
            produce = False
            for n in names:
                v = block._find_var_recursive(n)
                skip = (
                    n in no_grad
                    or not _is_float_var(block, n)
                    or (v is not None and v.stop_gradient and not isinstance(v, Parameter))
                )
                if skip:
                    outs.append(None)
                    continue
                gname = unique_name.generate(grad_var_name(n))
                _create_grad_var(block, n, gname)
                contribs.setdefault(n, []).append(gname)
                outs.append(gname)
                produce = True
            if produce:
                gout[slot + "@GRAD"] = ["" if o is None else o for o in outs]
        if not gout:
            continue

        # note: grad-output name lists keep positional alignment with the
        # forward input slots ("" = no grad wanted); the tracer skips empties
        block.append_op(
            op.type + "_grad",
            inputs=gin,
            outputs=gout,
            attrs={
                "__fwd_type__": op.type,
                "__fwd_attrs__": dict(op.attrs),
                "__fwd_in_slots__": list(op.inputs.keys()),
                "__fwd_out_slots__": list(op.outputs.keys()),
                "__fwd_out_names__": {k: list(v) for k, v in op.outputs.items()},
                "__fwd_op_idx__": i,
            },
        )

        # in-place updates (a var both read and written by this op — loop
        # carries, assign-into-existing) violate the one-writer assumption
        # the name-keyed accumulator relies on: contributions gathered so
        # far belong to the POST-op version and were just consumed as this
        # op's output grad.  Earlier ops must see only the grad this op
        # produced for its (pre-op) input version.
        in_names = set(op.input_arg_names())
        for n in set(op.output_arg_names()) & in_names:
            if not _is_float_var(block, n):
                continue
            newg = None
            for slot, names in op.inputs.items():
                gnames = gout.get(slot + "@GRAD")
                if not gnames:
                    continue
                for nm, g in zip(names, gnames):
                    if nm == n and g:
                        newg = g
            contribs[n] = [newg] if newg else []
            finalized.pop(n, None)

    # finalize every remaining accumulated grad and publish the name map so
    # calc_gradient (and debuggers) can find grads of arbitrary vars;
    # unconsumed sum ops are dropped by executor DCE
    for name in list(contribs.keys()):
        finalize(name)
    if not hasattr(program, "_grad_names"):
        program._grad_names = {}
    program._grad_names.update(finalized)

    # collect parameter grads
    if parameter_list is not None:
        params = [
            block._find_var_recursive(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [
            v
            for v in block.vars.values()
            if isinstance(v, Parameter) and v.trainable
        ]
    params_grads = []
    for p in params:
        g = finalize(p.name)
        if g is None:
            continue
        gv = block._find_var_recursive(g)
        params_grads.append((p, gv))
    return params_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradient of targets w.r.t. arbitrary inputs (backward.py calc_gradient)."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    assert len(targets) == 1, "calc_gradient: single target supported"
    append_backward(targets[0], parameter_list=None, no_grad_set=no_grad_set)
    block = targets[0].block
    grad_map = getattr(block.program, "_grad_names", {})
    outs = []
    for iv in inputs:
        gname = grad_map.get(iv.name)
        outs.append(block._find_var_recursive(gname) if gname else None)
    return outs
