"""Core execution engine: op registry, block tracer, scope, compile cache."""

from . import registry, scope, trace
from .scope import Scope, global_scope, scope_guard
from ..reader.program_reader import EOFException  # fluid.core.EOFException parity
