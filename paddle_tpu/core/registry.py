"""Op registry: op type -> JAX lowering rule.

TPU-native analog of the reference's kernel registry
(``paddle/fluid/framework/op_registry.h``).  Where the reference maps
``op_type -> {OpKernelType -> kernel fn}`` and dispatches per-op at runtime,
here each op registers one *lowering rule*: a pure function from traced JAX
arrays (+ static attrs) to traced JAX arrays.  The executor composes these
rules while tracing a Block and XLA compiles/fuses the whole block.

Gradients come from the lowering itself: for any op ``foo``, the op
``foo_grad`` is lowered generically via ``jax.vjp`` of foo's lowering — the
TPU replacement for the reference's per-op ``GradOpDescMaker`` + hand-written
grad kernels (``grad_op_desc_maker.h``).  XLA CSE merges the re-traced
forward with the original, so no double compute survives compilation.
"""


import contextlib
import threading

import jax
import jax.numpy as jnp
import numpy as np

# --- microbatch-rows context -------------------------------------------------
# Pipeline stage tracing sets this around each stage application so
# row-wise randomness (dropout) stays bit-identical to the unpipelined
# program: the op draws its mask over the FULL global batch rows and
# slices out the local microbatch's window.  threefry is counter-based
# per array position, so the full-batch draw is the same no matter which
# device traces it.  Only meaningful for batch-leading tensors; it is
# only ever set during pipeline stage traces.
_MB_ROWS = threading.local()


@contextlib.contextmanager
def microbatch_rows(total_rows, row_offset):
    """Bind (total global batch rows, this microbatch's first row) for the
    enclosed trace.  `row_offset` may be a traced value."""
    prev = getattr(_MB_ROWS, "ctx", None)
    _MB_ROWS.ctx = (total_rows, row_offset)
    try:
        yield
    finally:
        _MB_ROWS.ctx = prev


def current_microbatch_rows():
    """(total_rows, row_offset) when inside microbatch_rows(), else None."""
    return getattr(_MB_ROWS, "ctx", None)


class OpDef:
    def __init__(
        self, type, lower, no_grad_inputs=None, needs_rng=False,
        side_effect=False, handles_selected_rows=False,
    ):
        self.type = type
        self.lower = lower  # fn(ctx, ins: {slot: [arrays]}, attrs) -> {slot: [arrays]}
        self.no_grad_inputs = set(no_grad_inputs or ())
        self.needs_rng = needs_rng
        # side-effecting ops (network sends, barriers) survive DCE even when
        # no fetch depends on their outputs
        self.side_effect = side_effect
        # ops that natively consume SelectedRows sparse grads (the analog of
        # the reference kernels specialized on the SELECTED_ROWS var type);
        # all other ops get inputs densified by the tracer
        self.handles_selected_rows = handles_selected_rows


OPS = {}


def register(type_, no_grad_inputs=None, needs_rng=False, side_effect=False,
             handles_selected_rows=False):
    """Decorator: register a lowering rule for op `type_`."""

    def deco(fn):
        OPS[type_] = OpDef(type_, fn, no_grad_inputs, needs_rng, side_effect,
                           handles_selected_rows)
        return fn

    return deco


def get_op(type_):
    if type_ not in OPS:
        raise NotImplementedError(
            "op '%s' has no TPU lowering registered (known: %d ops)"
            % (type_, len(OPS))
        )
    return OPS[type_]


def is_registered(type_):
    return type_ in OPS


class LowerCtx:
    """Per-trace context handed to lowering rules.

    Carries the step RNG key (ops fold in their op index for independent
    streams — the analog of the reference's per-op seed attrs) and trace-wide
    flags.
    """

    def __init__(self, rng_key=None, is_test=False, scope=None):
        self.rng_key = rng_key
        self.is_test = is_test
        self.scope = scope
        self.op_idx = 0
        self.block = None
        self.trace_block = None  # fn(block_idx, env) for control-flow ops

    def rng(self, attrs=None, salt=0):
        """Key for a randomness-consuming op.  The step key (rng_key, which
        the executor advances every run) is always in the mix so seeded
        dropout still varies per step; a nonzero `seed` attr replaces the
        op-position fold so ops sharing a seed share a stream (reference
        per-op seed-attr semantics)."""
        seed = int(attrs.get("seed", 0)) if attrs else 0
        key = self.rng_key if self.rng_key is not None else jax.random.PRNGKey(0)
        if seed:
            key = jax.random.fold_in(key, seed)
        else:
            key = jax.random.fold_in(key, self.op_idx)
        return jax.random.fold_in(key, salt)


def _is_float(x):
    try:
        return jnp.issubdtype(jnp.result_type(x), jnp.floating)
    except TypeError:
        return False  # opaque values (TensorArray) are not differentiable leaves


def lower_grad_op(ctx, op, ins, attrs):
    """Generic lowering for `<type>_grad` ops via jax.vjp of the forward rule.

    The grad OpDesc (built by backward.py) carries bookkeeping attrs:
      __fwd_type__     : forward op type
      __fwd_attrs__    : forward attrs
      __fwd_in_slots__ : forward input slot names present
      __fwd_out_slots__: forward output slot names
      __fwd_op_idx__   : forward op's index (for RNG parity, e.g. dropout)
    Inputs: forward inputs under their slot names, plus `<out-slot>@GRAD`.
    Outputs: `<in-slot>@GRAD` for differentiable (float) inputs.
    """
    fwd_type = attrs["__fwd_type__"]
    fwd_attrs = attrs["__fwd_attrs__"]
    in_slots = attrs["__fwd_in_slots__"]
    out_slots = attrs["__fwd_out_slots__"]
    opdef = get_op(fwd_type)

    fwd_ins = {s: ins[s] for s in in_slots if s in ins}

    # differentiable leaf positions: float-dtype arrays in forward inputs,
    # minus slots the op marks non-differentiable (e.g. lookup_table Ids)
    diff_pos = []  # (slot, idx)
    for s in in_slots:
        if s in opdef.no_grad_inputs or s not in fwd_ins:
            continue
        for i, v in enumerate(fwd_ins[s]):
            if _is_float(v):
                diff_pos.append((s, i))

    sub_ctx = LowerCtx(ctx.rng_key, ctx.is_test, ctx.scope)
    sub_ctx.op_idx = attrs.get("__fwd_op_idx__", ctx.op_idx)
    sub_ctx.trace_block = ctx.trace_block
    # mesh-aware lowerings resolve the forward OpDesc (weight names ->
    # partition specs) through ctx.block + op_idx; the grad-side re-run
    # of the forward rule must see the same block or they fall back to
    # replicated operands
    sub_ctx.block = ctx.block

    def fwd_fn(diff_vals):
        merged = {s: list(v) for s, v in fwd_ins.items()}
        for (s, i), v in zip(diff_pos, diff_vals):
            merged[s][i] = v
        outs = opdef.lower(sub_ctx, merged, fwd_attrs)
        flat = []
        for s in out_slots:
            for o in outs.get(s, []):
                flat.append(o)
        return flat

    primals = [fwd_ins[s][i] for (s, i) in diff_pos]
    fwd_flat, vjp_fn = jax.vjp(fwd_fn, primals)

    # cotangents: supplied grads or zeros; non-float outputs (indices, loop
    # conditions) take symbolic-zero float0 cotangents per jax.vjp contract
    cots = []
    k = 0
    for s in out_slots:
        n_out = len(attrs.get("__fwd_out_names__", {}).get(s, [None]))
        gslot = ins.get(s + "@GRAD")
        for i in range(n_out):
            ref = fwd_flat[k]
            k += 1
            if not jnp.issubdtype(jnp.result_type(ref), jnp.inexact):
                cots.append(np.zeros(ref.shape, dtype=jax.dtypes.float0))
            elif gslot is not None and i < len(gslot) and gslot[i] is not None:
                cots.append(jnp.asarray(gslot[i], dtype=ref.dtype).reshape(ref.shape))
            else:
                cots.append(jnp.zeros(ref.shape, ref.dtype))
    (grads,) = vjp_fn(cots)

    outs = {}
    for (s, i), g in zip(diff_pos, grads):
        outs.setdefault(s + "@GRAD", {})[i] = g
    # normalize to lists
    result = {}
    for s, d in outs.items():
        n = max(d.keys()) + 1
        result[s] = [d.get(i) for i in range(n)]
    return result
