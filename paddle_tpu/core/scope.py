"""Runtime Scope: name -> value store (scope.h:41 analog).

The reference's Scope is a hierarchical map of type-erased Variables that the
interpreting executor mutates in place.  Here values are JAX arrays living in
TPU HBM (or host numpy); the executor functionalizes mutation — a step's
updated state is written back here after the compiled function returns, with
donation making the HBM update in-place.
"""



class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []

    def var(self, name):
        """Get-or-create (mirrors Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def has_var(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s.parent
        return False

    def get(self, name):
        return self.find_var(name)

    def set(self, name, value):
        # write where the var already exists, else locally
        s = self
        while s is not None:
            if name in s._vars:
                s._vars[name] = value
                return
            s = s.parent
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars.keys())

    def all_var_names(self):
        names = []
        s = self
        while s is not None:
            names.extend(s._vars.keys())
            s = s.parent
        return names


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


def _switch_scope(scope):
    global _scope_stack
    prev = _scope_stack[-1]
    _scope_stack[-1] = scope
    return prev


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    prev = _switch_scope(scope)
    try:
        yield
    finally:
        _switch_scope(prev)
