"""Block tracer + XLA compile cache — the heart of the execution engine.

This replaces the reference's interpreting ``Executor``
(``paddle/fluid/framework/executor.cc:380`` hot loop: per-op InferShape +
kernel dispatch) with a compile-first design: a Block's op sequence is traced
symbolically through the op lowering rules into a single pure JAX function

    f(feeds, ro_state, rw_state, rng_key) -> (fetches, new_state)

which ``jax.jit`` compiles once per (program version, input signature) and
caches — Executor::Prepare + the kernel loop collapsing into one XLA
executable.  Scope mutation (parameter updates, BN running stats, optimizer
state) is functionalized: every scope variable an op writes becomes an output
threaded back into the scope after the step.  ``rw_state`` (read+written
vars — parameters under training) is donated, so updates alias in HBM; pure
reads (``ro_state``) are not donated and stay valid across steps.
"""

import jax
import jax.numpy as jnp

from .registry import OPS, LowerCtx, get_op, lower_grad_op
from .selected_rows import SelectedRows, densify_maybe


class _TraceContextError(RuntimeError):
    """Lowering failure annotated with op/block/shape context
    (PADDLE_ENFORCE error-context discipline, platform/enforce.h)."""


class TracedFunction:
    def __init__(self, fn, feed_names, ro_names, rw_names, fetch_names, updated):
        self.fn = fn
        self.feed_names = feed_names
        self.ro_names = ro_names
        self.rw_names = rw_names
        self.fetch_names = fetch_names
        self.updated = updated


def dce_mask(program, block_idx, fetch_names):
    """Dead-code elimination: keep ops reachable from the fetch targets or
    writing persistable state (optimizer updates, BN stats, counters run
    unconditionally, matching interpreter side-effect semantics).  The
    analog of Program pruning (prune.cc) done implicitly per execution."""
    blk = program.block(block_idx)

    def is_persistable(name):
        v = blk._find_var_recursive(name)
        return v is not None and v.persistable

    # test-mode programs (clone(for_test=True)) never run training-only
    # ops, even though those write persistable state (fluid semantics:
    # Program.clone strips nothing, but an is_test run must not step the
    # optimizer or touch grads)
    is_test = getattr(program, "_is_test", False)
    train_roles = ("backward", "optimize", "lrsched", "loss", "rpc")

    needed = set(fetch_names)
    keep = [False] * len(blk.ops)
    for i in range(len(blk.ops) - 1, -1, -1):
        op = blk.ops[i]
        if is_test and op.attrs.get("op_role") in train_roles:
            continue
        outs = op.output_arg_names()
        opdef = OPS.get(op.type)
        if (
            any(n in needed for n in outs)
            or any(is_persistable(n) for n in outs)
            or (opdef is not None and opdef.side_effect)
        ):
            keep[i] = True
            needed.update(op.input_arg_names())
    return keep


def op_sub_blocks(op):
    """Sub-block indices owned by an op — THE discovery primitive every
    block analyzer shares (visit_reads_writes, the IfElse branch-effect
    guard): any `sub_block*` attr, int-valued (while/cond/recurrent) or
    list-valued (switch's sub_block_idxs)."""
    out = []
    for a, v in op.attrs.items():
        if not a.startswith("sub_block"):
            continue
        if isinstance(v, int):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(int(i) for i in v)
    return out


def visit_reads_writes(program, bidx, defined, on_read, on_write=None, pre_op=None):
    """Shared block traversal: report names read before being written
    (recursing into sub_block attrs, whose `__bound_names__` — recurrent
    step slices, carried loop state — are defined by the op's lowering,
    not external reads).  `pre_op(bidx, i, op)` may return "skip" to drop
    an op or "define" to treat its outputs as given (feed/read ops)."""
    blk = program.block(bidx)
    for i, op in enumerate(blk.ops):
        if pre_op is not None:
            action = pre_op(bidx, i, op)
            if action == "skip":
                continue
            if action == "define":
                for n in op.output_arg_names():
                    defined.add(n)
                continue
        for name in op.input_arg_names():
            if name and name not in defined:
                on_read(name)
        for sub_idx in op_sub_blocks(op):
            bound = op.attrs.get("__bound_names__", ())
            visit_reads_writes(
                program, sub_idx, set(defined) | set(bound), on_read,
                on_write, pre_op
            )
        for name in op.output_arg_names():
            defined.add(name)
            if on_write is not None:
                on_write(name)


def sub_block_external_reads(program, block, bound):
    """Outer-scope names a sub-block (incl. nested) reads before writing —
    what a sub-block-owning op must declare as inputs (layer-build-time
    counterpart of analyze_block's trace-time discovery)."""
    reads = []
    seen = set()

    def on_read(n):
        if n not in seen:
            seen.add(n)
            reads.append(n)

    visit_reads_writes(program, block.idx, set(bound), on_read)
    return reads


def analyze_block(program, block_idx, feed_names, fetch_names, keep=None):
    """Find external reads (scope state the block consumes) and all writes,
    across the block and its sub-blocks."""
    reads = []
    reads_set = set()
    writes = []
    writes_set = set()

    def on_read(name):
        if name not in reads_set:
            reads_set.add(name)
            reads.append(name)

    def on_write(name):
        if name not in writes_set:
            writes_set.add(name)
            writes.append(name)

    def pre_op(bidx, i, op):
        if keep is not None and bidx == block_idx and not keep[i]:
            return "skip"
        if op.type in ("feed", "read"):
            # read-op outputs arrive as implicit feeds (executor pops the
            # reader queue); the Reader var itself is host state
            return "define"
        return None

    visit_reads_writes(
        program, block_idx, set(feed_names), on_read, on_write, pre_op
    )
    for n in fetch_names:
        if n not in writes_set and n not in set(feed_names) and n not in reads_set:
            reads_set.add(n)
            reads.append(n)
    return reads, writes


def build_traced_function(program, block_idx, feed_names, fetch_names, scope,
                          collective_axis=None, spmd=None, keep=None):
    """`collective_axis`: optional ("axis_name", nranks) pair binding the
    collective-lowering context around the trace — c_allreduce_* ops then
    lower to jax.lax collectives over that axis instead of identity.  The
    caller (executor._run_collective) is responsible for actually running
    the traced fn under a shard_map that binds the axis.

    `spmd`: optional (mesh, PartitionRules) pair binding the GSPMD
    lowering context (parallel.partition_rules.spmd_lowering) around the
    trace — mesh-aware lowerings (fused_attention's vector-QStart
    branch, slot_cache_write) then emit shard_map-wrapped kernels /
    sharding constraints.  The caller (executor._run_spmd) jits the
    traced fn with the rule table's in/out shardings.

    `keep`: optional explicit per-op keep mask for `block_idx`, replacing
    the internal DCE mask.  Pipeline stage slicing passes its own masks so
    a stage traces exactly its op range — DCE would otherwise drag the
    whole optimizer chain in through persistable writes."""
    if keep is None:
        keep = dce_mask(program, block_idx, fetch_names)
    reads, writes = analyze_block(program, block_idx, feed_names, fetch_names, keep)
    state_names = [n for n in reads if scope.has_var(n)]
    missing = [n for n in reads if not scope.has_var(n)]
    if missing:
        raise RuntimeError(
            "variables %s are read by the program but neither fed nor found in "
            "scope — run the startup program first" % missing
        )
    block = program.block(block_idx)

    def is_persistable(name):
        v = block._find_var_recursive(name)
        return v is not None and v.persistable

    state_set = set(state_names)
    # updated = state that is rewritten, plus fresh persistable writes
    # (optimizer accumulators created mid-program)
    updated = [n for n in writes if n in state_set or is_persistable(n)]
    rw_names = [n for n in state_names if n in set(updated)]
    ro_names = [n for n in state_names if n not in set(updated)]
    is_test = getattr(program, "_is_test", False)

    def fn(feeds, ro_state, rw_state, rng_key):
        if collective_axis is not None:
            from ..parallel.collective import collective_lowering

            with collective_lowering(*collective_axis):
                return _fn_body(feeds, ro_state, rw_state, rng_key)
        if spmd is not None:
            from ..parallel.partition_rules import spmd_lowering

            with spmd_lowering(*spmd):
                return _fn_body(feeds, ro_state, rw_state, rng_key)
        return _fn_body(feeds, ro_state, rw_state, rng_key)

    def _fn_body(feeds, ro_state, rw_state, rng_key):
        env = {}
        env.update(ro_state)
        env.update(rw_state)
        env.update(feeds)
        ctx = LowerCtx(rng_key=rng_key, is_test=is_test, scope=scope)

        def trace_while(op, env):
            """Lower a `while` op to lax.while_loop (while_op.cc:36 analog:
            the sub-block interpreter + StepScopes collapse into compiled
            XLA control flow).  Loop state = the op's carried_vars; the
            condition var must be recomputed inside the body (fluid's
            `layers.less_than(..., cond=cond)` idiom ensures this)."""
            sub_idx = op.attrs["sub_block_idx"]
            carried = list(op.attrs["carried_vars"])
            cond_name = op.inputs["Condition"][0]
            if cond_name not in carried:
                raise RuntimeError(
                    "While condition var '%s' is not updated in the loop body "
                    "(infinite loop); recompute it with layers.less_than(..., "
                    "cond=cond)" % cond_name
                )

            def cond_fn(carry):
                return jnp.reshape(carry[carried.index(cond_name)], ()).astype(bool)

            def body_fn(carry):
                env2 = dict(env)
                env2.update(zip(carried, carry))
                env2 = trace_ops(sub_idx, env2)
                return tuple(env2[n] for n in carried)

            init = tuple(env[n] for n in carried)
            out = jax.lax.while_loop(cond_fn, body_fn, init)
            env.update(zip(carried, out))
            return env

        def trace_cond(op, env):
            """Lower a `cond` op to lax.cond; branch sub-blocks close over
            the outer env, outputs are the declared branch result vars."""
            pred = jnp.reshape(env[op.inputs["Condition"][0]], ()).astype(bool)
            tidx = op.attrs["sub_block_true_idx"]
            fidx = op.attrs["sub_block_false_idx"]
            touts = op.attrs["true_outs"]
            fouts = op.attrs["false_outs"]

            def tf(_):
                return tuple(trace_ops(tidx, dict(env))[n] for n in touts)

            def ff(_):
                return tuple(trace_ops(fidx, dict(env))[n] for n in fouts)

            outs = jax.lax.cond(pred, tf, ff, None)
            for n, v in zip(op.outputs["Out"], outs):
                env[n] = v
            return env

        # pre-execution input snapshots for ops that overwrite their own
        # inputs (loop carries, assign-into-existing): their grad ops re-run
        # the forward lowering and MUST see the original inputs, not the
        # post-op values the in-place write left in env
        snapshots = {}

        def trace_ops(bidx, env):
            blk = program.block(bidx)
            for idx, op in enumerate(blk.ops):
                if op.type in ("feed", "fetch", "read", "create_py_reader"):
                    continue  # satisfied as implicit feeds / host state
                if bidx == block_idx and not keep[idx]:
                    continue
                ctx.op_idx = (bidx << 20) | idx
                ctx.block = blk
                if op.type == "while":
                    env = trace_while(op, env)
                    continue
                if op.type == "cond":
                    env = trace_cond(op, env)
                    continue
                is_grad = op.type.endswith("_grad") and "__fwd_type__" in op.attrs
                snap = None
                if is_grad:
                    snap = snapshots.get((bidx, op.attrs.get("__fwd_op_idx__")))
                elif set(op.output_arg_names()) & set(op.input_arg_names()):
                    snapshots[(bidx, idx)] = {
                        n: env[n] for n in op.input_arg_names() if n in env
                    }
                ins = {}
                for slot, names in op.inputs.items():
                    vals = []
                    use_snap = snap if not slot.endswith("@GRAD") else None
                    for n in names:
                        if use_snap is not None and n in use_snap:
                            vals.append(use_snap[n])
                            continue
                        if n not in env:
                            raise RuntimeError(
                                "op %s reads undefined var %s" % (op.type, n)
                            )
                        vals.append(env[n])
                    ins[slot] = vals
                try:
                    opdef = OPS.get(op.type)
                    # SelectedRows inputs densify automatically for ops that
                    # don't declare native support (reference: kernels not
                    # specialized on SELECTED_ROWS see a dense tensor)
                    if any(
                        isinstance(v, SelectedRows)
                        for vals in ins.values() for v in vals
                    ) and not (opdef is not None
                               and opdef.handles_selected_rows):
                        ins = {
                            s: [densify_maybe(v) for v in vals]
                            for s, vals in ins.items()
                        }
                    if opdef is not None:
                        outs = opdef.lower(ctx, ins, op.attrs)
                    elif (op.type.endswith("_grad")
                          and "__fwd_type__" in op.attrs):
                        outs = lower_grad_op(ctx, op, ins, op.attrs)
                    else:
                        outs = get_op(op.type).lower(ctx, ins, op.attrs)
                except Exception as e:
                    # PADDLE_ENFORCE-style error context (enforce.h): name
                    # the op and its inputs so a shape/dtype error inside a
                    # compiled block is attributable without reading XLA
                    # internals.  Tracer-context errors pass through.
                    if isinstance(e, _TraceContextError):
                        raise
                    shapes = {
                        slot: [getattr(v, "shape", "?") for v in vals]
                        for slot, vals in ins.items()
                    }
                    raise _TraceContextError(
                        "while lowering op '%s' (block %d, op %d) with input "
                        "shapes %s: %s: %s"
                        % (op.type, bidx, idx, shapes, type(e).__name__, e)
                    ) from e
                for slot, names in op.outputs.items():
                    vals = outs.get(slot)
                    if vals is None:
                        continue
                    for n, v in zip(names, vals):
                        if n and v is not None:
                            env[n] = v
            return env

        ctx.trace_block = trace_ops
        env = trace_ops(block_idx, env)

        fetches = []
        for n in fetch_names:
            if n not in env:
                raise RuntimeError("fetch var %s was never produced" % n)
            fetches.append(densify_maybe(env[n]))
        new_state = {n: densify_maybe(env[n]) for n in updated if n in env}
        return fetches, new_state

    return TracedFunction(fn, list(feed_names), ro_names, rw_names, fetch_names, updated)


class CompiledBlock:
    """One XLA executable for (program version, block, signature)."""

    def __init__(self, traced, jitted):
        self.traced = traced
        self.jitted = jitted

    def __call__(self, feeds, ro_state, rw_state, rng_key):
        return self.jitted(feeds, ro_state, rw_state, rng_key)


class ExecutionCache:
    """Compile cache keyed by (program id, version, feed signature) — the
    analog of Executor::Prepare context reuse + XLA executable caching."""

    def __init__(self):
        self._cache = {}
        # monotone count of cache MISSES (fresh traces) — the serving
        # engine's compiles-once contract is asserted against this:
        # occupancy churn must change feed VALUES only, never keys
        self.compile_count = 0

    def get(self, program, block_idx, feed_sig, fetch_names, scope, donate=True):
        # flags that change lowering decisions are part of the compile key —
        # toggling them must recompile, not hit a stale executable
        from ..flags import get_flag

        key = (
            id(program),
            program._version,
            block_idx,
            feed_sig,
            tuple(fetch_names),
            id(scope),
            bool(get_flag("use_pallas")),
            get_flag("prng_impl"),
        )
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        self.compile_count += 1
        feed_names = tuple(n for n, _, _ in feed_sig)
        traced = build_traced_function(
            program, block_idx, feed_names, fetch_names, scope
        )
        jitted = jax.jit(traced.fn, donate_argnums=(2,) if donate else ())
        compiled = CompiledBlock(traced, jitted)
        self._cache[key] = compiled
        return compiled

    def clear(self):
        self._cache.clear()
