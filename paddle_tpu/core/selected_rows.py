"""SelectedRows — the sparse row-subset gradient representation.

TPU-native analog of the reference's ``SelectedRows``
(``paddle/fluid/framework/selected_rows.h:32``): a (rows, value) pair
standing for a ``[height, ...]`` tensor that is zero outside ``rows``.
``lookup_table_grad`` emits one (as ``lookup_table_op.cc`` does), and the
sparse-aware optimizer lowerings (sgd/adam/adagrad — the reference's
``operators/optimizers/adam_op.h``/``sgd_op.h`` SelectedRows branches)
apply segment updates to just the touched rows, so a word2vec/CTR-scale
vocab never materializes a ``[vocab, dim]`` gradient in HBM.

Registered as a JAX pytree, so it flows through jit/trace like any array
pair.  Ops that don't declare ``handles_selected_rows`` receive the
densified tensor automatically (trace-time fallback).

Duplicate ids are legal in ``rows`` (one occurrence per lookup position);
``merged()`` combines duplicates by summation — required before any
non-linear optimizer math.  Padding slots use row index == height and are
dropped by the ``mode="drop"`` scatters.
"""

import jax
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
class SelectedRows:
    def __init__(self, rows, value, height):
        self.rows = rows  # int32 [N]
        self.value = value  # [N, d...]
        self.height = int(height)  # static: the dense leading dim

    def tree_flatten(self):
        return (self.rows, self.value), self.height

    @classmethod
    def tree_unflatten(cls, height, children):
        return cls(children[0], children[1], height)

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def dense_shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def densify(self):
        """Scatter-add into the full [height, ...] tensor."""
        out = jnp.zeros(self.dense_shape, self.value.dtype)
        return out.at[self.rows].add(self.value, mode="drop")

    def scaled(self, s):
        return SelectedRows(self.rows, self.value * s, self.height)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.value.astype(dtype), self.height)

    def merged(self):
        """Combine duplicate rows by summation (static [N] shapes: sort,
        segment-sum into compacted slots; tail padding rows get index ==
        height, which every consumer scatters with mode='drop')."""
        n = self.rows.shape[0]
        if n == 0:
            return self
        order = jnp.argsort(self.rows)
        r = self.rows[order]
        v = self.value[order]
        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), r[1:] != r[:-1]])
        seg = jnp.cumsum(is_new) - 1  # compacted slot per entry
        mv = jax.ops.segment_sum(v, seg, num_segments=n)
        mr = jnp.full((n,), self.height, jnp.int32).at[seg].set(r)
        return SelectedRows(mr, mv, self.height)


def densify_maybe(x):
    return x.densify() if isinstance(x, SelectedRows) else x
