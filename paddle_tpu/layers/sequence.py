"""Sequence layers over the padded+lengths representation.

The reference's 46 LoD-aware sequence ops (operators/sequence_ops/) operate
on concatenated ragged tensors.  TPU-natively, sequences are padded
[batch, time, ...] arrays with an optional per-example length tensor; masks
replace LoD offsets (SURVEY.md §5.7).  Layers accept an explicit `seq_len`
variable; without one, the full time axis is used.
"""

import jax.numpy as jnp

from ..core.registry import register
from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv",

    "sequence_pool",
    "sequence_softmax",
    "sequence_expand",
    "sequence_concat",
    "sequence_reshape",
    "sequence_pad",
    "sequence_unpad",
    "sequence_mask",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_reverse",
    "sequence_slice",
]


# ---------------------------------------------------------------------------
# op lowerings (registered here, close to the layers)
# ---------------------------------------------------------------------------
def _time_mask(x, seq_len):
    """[B, T] float mask from lengths."""
    t = x.shape[1]
    ar = jnp.arange(t)[None, :]
    return (ar < seq_len[:, None]).astype(x.dtype)


@register("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T, ...]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    if seq_len is None:
        mask = jnp.ones(x.shape[:2], x.dtype)
    else:
        mask = _time_mask(x, seq_len)
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape)
    if ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / jnp.maximum(
            jnp.sum(m, axis=1), 1.0
        )
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(
            jnp.maximum(jnp.sum(m, axis=1), 1.0)
        )
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min
        out = jnp.max(jnp.where(m > 0, x, neg), axis=1)
    elif ptype == "LAST":
        if seq_len is None:
            out = x[:, -1]
        else:
            idx = jnp.maximum(seq_len - 1, 0).astype(jnp.int32)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
            )[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise NotImplementedError("sequence_pool type %s" % ptype)
    return {"Out": [out]}


@register("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]  # [B, T] or [B, T, 1]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    squeeze = x.ndim == 3
    xx = x[..., 0] if squeeze else x
    if seq_len is not None:
        mask = _time_mask(xx, seq_len)
        xx = jnp.where(mask > 0, xx, jnp.finfo(xx.dtype).min)
    out = jax_softmax(xx)
    if seq_len is not None:
        out = out * mask
    if squeeze:
        out = out[..., None]
    return {"Out": [out]}


def jax_softmax(x):
    import jax

    return jax.nn.softmax(x, axis=-1)


@register("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0] if ins.get("SeqLen") else None
    if seq_len is None:
        return {"Y": [jnp.flip(x, axis=1)]}
    t = x.shape[1]
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < seq_len[:, None], seq_len[:, None] - 1 - ar, ar)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32)
    return {"Y": [jnp.take_along_axis(x, jnp.broadcast_to(idx, x.shape), axis=1)]}


@register("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    # padded semantics: broadcast x [B, ...] to y's time axis [B, T, ...]
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == y.ndim:
        return {"Out": [jnp.broadcast_to(x, y.shape)]}
    out = jnp.broadcast_to(x[:, None], (x.shape[0], y.shape[1]) + x.shape[1:])
    return {"Out": [out]}


@register("sequence_mask", no_grad_inputs=("X",))
def _sequence_mask(ctx, ins, attrs):
    x = ins["X"][0]  # lengths
    maxlen = attrs.get("maxlen", -1)
    if maxlen < 0:
        raise NotImplementedError("sequence_mask requires static maxlen on TPU")
    ar = jnp.arange(maxlen)
    mask = (ar[None, :] < x[:, None]).astype(jnp.float32)
    return {"Y": [mask]}


# ---------------------------------------------------------------------------
# layers
# ---------------------------------------------------------------------------
def _seq_op(op_type, input, seq_len=None, attrs=None, out_slot="Out"):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(op_type, inputs=inputs, outputs={out_slot: [out]}, attrs=attrs or {})
    return out


def sequence_pool(input, pool_type, seq_len=None):
    return _seq_op("sequence_pool", input, seq_len, {"pooltype": pool_type.upper()})


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "FIRST", seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "LAST", seq_len)


def sequence_softmax(input, seq_len=None, use_cudnn=False, name=None):
    return _seq_op("sequence_softmax", input, seq_len)


def sequence_reverse(x, seq_len=None, name=None):
    return _seq_op("sequence_reverse", x, seq_len, out_slot="Y")


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sequence_expand", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def sequence_concat(input, name=None):
    from . import tensor as tensor_layers

    return tensor_layers.concat(input, axis=1)


def sequence_reshape(input, new_dim):
    from . import nn

    b = input.shape[0]
    return nn.reshape(input, [b, -1, new_dim])


def sequence_pad(x, pad_value, maxlen=None, name=None):
    # already padded in this representation
    return x, None


def sequence_unpad(x, length, name=None):
    return x


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "sequence_mask",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={"maxlen": maxlen if maxlen is not None else -1},
    )
    return out


def sequence_slice(input, offset, length, name=None):
    """Per-sequence window slice (sequence_slice_op.cc): keep the window
    [offset, offset+length) of each row, front-aligned in the padded
    representation.  Returns the sliced tensor; the new lengths tensor is
    available as ``out.seq_len``."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sequence_slice",
        inputs={"X": [input], "Offset": [offset], "Length": [length]},
        outputs={"Out": [out], "OutLen": [out_len]},
    )
    out.seq_len = out_len
    return out


def sequence_conv(
    input,
    num_filters,
    filter_size=3,
    filter_stride=1,
    padding=None,
    bias_attr=None,
    param_attr=None,
    act=None,
    seq_len=None,
    name=None,
):
    """Context-window sequence convolution (nn.py sequence_conv /
    sequence_conv_op.cc) over the padded [B, T, D] representation."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("sequence_conv", **locals())
    dtype = helper.input_dtype()
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [input], "Filter": [w]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        "sequence_conv",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "contextLength": filter_size,
            "contextStart": -int(filter_size // 2),
            "contextStride": filter_stride,
        },
    )
    pre_act = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(pre_act)
