"""Detection layers (layers/detection.py analog) — SSD/RCNN helpers.

Round-1 subset: prior_box, box_coder, iou. NMS-family ops are
dynamic-shape-heavy and pending a TPU-friendly (padded top-k) design.
"""

import numpy as np
import jax.numpy as jnp

from ..core.registry import register
from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "multiclass_nms", "ssd_loss"]


@register("prior_box", no_grad_inputs=("Input", "Image"))
def _prior_box(ctx, ins, attrs):
    feat, img = ins["Input"][0], ins["Image"][0]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    ars = []
    for r in ratios:
        ars.append(r)
        if flip and r != 1.0:
            ars.append(1.0 / r)
    boxes = []
    variances = []
    var = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    for y in range(h):
        for x in range(w):
            cx = (x + offset) * sw
            cy = (y + offset) * sh
            for k, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) / 2
                    bh = ms / np.sqrt(ar) / 2
                    boxes.append(
                        [(cx - bw) / img_w, (cy - bh) / img_h, (cx + bw) / img_w, (cy + bh) / img_h]
                    )
                    variances.append(var)
                if max_sizes:
                    bs = np.sqrt(ms * max_sizes[k]) / 2
                    boxes.append(
                        [(cx - bs) / img_w, (cy - bs) / img_h, (cx + bs) / img_w, (cy + bs) / img_h]
                    )
                    variances.append(var)
    boxes = np.array(boxes, np.float32).reshape(h, w, -1, 4)
    variances = np.array(variances, np.float32).reshape(h, w, -1, 4)
    if clip:
        boxes = np.clip(boxes, 0, 1)
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(variances)]}


@register("iou_similarity", no_grad_inputs=("X", "Y"))
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # [N,4],[M,4]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": [inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10)]}


def prior_box(
    input,
    image,
    min_sizes,
    max_sizes=None,
    aspect_ratios=[1.0],
    variance=[0.1, 0.1, 0.2, 0.2],
    flip=False,
    clip=False,
    steps=[0.0, 0.0],
    offset=0.5,
    name=None,
):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "iou_similarity", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size", box_normalized=True, name=None):
    raise NotImplementedError("box_coder pending")


def multiclass_nms(*args, **kwargs):
    raise NotImplementedError(
        "multiclass_nms pending a padded-topk TPU design (detection phase)"
    )


def ssd_loss(*args, **kwargs):
    raise NotImplementedError("ssd_loss pending (detection phase)")
