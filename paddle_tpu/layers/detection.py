"""Detection layers (layers/detection.py analog) — the SSD and RCNN
helper surface: priors (incl. density), box codecs, NMS/matching in the
padded static-shape form, proposal generation/labeling, roi pooling,
losses, mAP, and the multi_box_head composition."""

import numpy as np
import jax.numpy as jnp

from ..core.registry import register
from ..layer_helper import LayerHelper

__all__ = [
    "prior_box",
    "box_coder",
    "iou_similarity",
    "multiclass_nms",
    "ssd_loss",
    "detection_output",
    "generate_proposals",
    "rpn_target_assign",
    "generate_proposal_labels",
    "bipartite_match",
    "target_assign",
    "mine_hard_examples",
    "anchor_generator",
    "roi_pool",
    "roi_align",
    "roi_perspective_transform",
]


@register("prior_box", no_grad_inputs=("Input", "Image"))
def _prior_box(ctx, ins, attrs):
    feat, img = ins["Input"][0], ins["Image"][0]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    sw = step_w or img_w / w
    sh = step_h or img_h / h
    ars = []
    for r in ratios:
        ars.append(r)
        if flip and r != 1.0:
            ars.append(1.0 / r)
    boxes = []
    variances = []
    var = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    for y in range(h):
        for x in range(w):
            cx = (x + offset) * sw
            cy = (y + offset) * sh
            for k, ms in enumerate(min_sizes):
                for ar in ars:
                    bw = ms * np.sqrt(ar) / 2
                    bh = ms / np.sqrt(ar) / 2
                    boxes.append(
                        [(cx - bw) / img_w, (cy - bh) / img_h, (cx + bw) / img_w, (cy + bh) / img_h]
                    )
                    variances.append(var)
                if max_sizes:
                    bs = np.sqrt(ms * max_sizes[k]) / 2
                    boxes.append(
                        [(cx - bs) / img_w, (cy - bs) / img_h, (cx + bs) / img_w, (cy + bs) / img_h]
                    )
                    variances.append(var)
    boxes = np.array(boxes, np.float32).reshape(h, w, -1, 4)
    variances = np.array(variances, np.float32).reshape(h, w, -1, 4)
    if clip:
        boxes = np.clip(boxes, 0, 1)
    return {"Boxes": [jnp.asarray(boxes)], "Variances": [jnp.asarray(variances)]}


@register("iou_similarity", no_grad_inputs=("X", "Y"))
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # [N,4],[M,4]
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": [inter / (area_x[:, None] + area_y[None, :] - inter + 1e-10)]}


def prior_box(
    input,
    image,
    min_sizes,
    max_sizes=None,
    aspect_ratios=[1.0],
    variance=[0.1, 0.1, 0.2, 0.2],
    flip=False,
    clip=False,
    steps=[0.0, 0.0],
    offset=0.5,
    name=None,
):
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "iou_similarity", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}
    )
    return out


def box_coder(
    prior_box,
    prior_box_var,
    target_box,
    code_type="encode_center_size",
    box_normalized=True,
    name=None,
):
    """layers/detection.py box_coder parity (detection/box_coder_op.cc)."""
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference(target_box.dtype)
    inputs = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    helper.append_op(
        "box_coder",
        inputs=inputs,
        outputs={"OutputBox": [out]},
        attrs={"code_type": code_type, "box_normalized": box_normalized},
    )
    return out


def multiclass_nms(
    bboxes,
    scores,
    score_threshold=0.01,
    nms_top_k=400,
    keep_top_k=200,
    nms_threshold=0.3,
    normalized=True,
    nms_eta=1.0,
    background_label=0,
    name=None,
):
    """Padded NMS (multiclass_nms_op.cc): returns (out [N, keep_top_k, 6]
    rows of (label, score, x1, y1, x2, y2) padded with label=-1,
    rois_num [N]) — the fixed-shape re-expression of the reference's LoD
    output."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "multiclass_nms",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "NmsRoisNum": [num]},
        attrs={
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "background_label": background_label,
        },
    )
    return out, num


def detection_output(
    loc,
    scores,
    prior_box,
    prior_box_var,
    background_label=0,
    nms_threshold=0.3,
    nms_top_k=400,
    keep_top_k=200,
    score_threshold=0.01,
    nms_eta=1.0,
):
    """SSD inference head (layers/detection.py detection_output):
    decode predicted offsets onto priors, then multiclass NMS.
    loc [N, P, 4], scores [N, P, C] (softmax-ed here), priors [P, 4]."""
    from . import nn

    decoded = box_coder(
        prior_box, prior_box_var, loc, code_type="decode_center_size"
    )
    sm = nn.softmax(scores)
    sm = nn.transpose(sm, [0, 2, 1])  # [N, C, P]
    out, num = multiclass_nms(
        decoded,
        sm,
        score_threshold=score_threshold,
        nms_top_k=nms_top_k,
        keep_top_k=keep_top_k,
        nms_threshold=nms_threshold,
        background_label=background_label,
    )
    return out


def ssd_loss(
    location,
    confidence,
    gt_box,
    gt_label,
    prior_box,
    prior_box_var=None,
    background_label=0,
    overlap_threshold=0.5,
    neg_pos_ratio=3.0,
    neg_overlap=0.5,
    loc_loss_weight=1.0,
    conf_loss_weight=1.0,
    match_type="per_prediction",
    mining_type="max_negative",
    normalize=True,
    sample_size=None,
    gt_num=None,
    name=None,
):
    """SSD multibox loss (layers/detection.py ssd_loss parity).

    Padded contract: location [N, P, 4], confidence [N, P, C],
    gt_box [N, G, 4], gt_label [N, G, 1] (zero-padded; pass gt_num [N] for
    real counts).  Returns per-prior loss [N, P] — the fused dense
    re-expression of the reference's iou/match/assign/mine/loss pipeline
    (one XLA kernel; see ops/detection_ops.py:_ssd_loss).
    """
    helper = LayerHelper("ssd_loss", name=name)
    out = helper.create_variable_for_type_inference(location.dtype)
    inputs = {
        "Location": [location],
        "Confidence": [confidence],
        "GtBox": [gt_box],
        "GtLabel": [gt_label],
        "PriorBox": [prior_box],
    }
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var]
    if gt_num is not None:
        inputs["GtNum"] = [gt_num]
    helper.append_op(
        "ssd_loss",
        inputs=inputs,
        outputs={"Loss": [out]},
        attrs={
            "overlap_threshold": overlap_threshold,
            "neg_pos_ratio": neg_pos_ratio,
            "background_label": background_label,
            "loc_loss_weight": loc_loss_weight,
            "conf_loss_weight": conf_loss_weight,
            "normalize": normalize,
        },
    )
    return out


def generate_proposals(
    scores,
    bbox_deltas,
    im_info,
    anchors,
    variances,
    pre_nms_top_n=6000,
    post_nms_top_n=1000,
    nms_thresh=0.5,
    min_size=0.1,
    eta=1.0,
    name=None,
):
    """RPN proposals (generate_proposals_op.cc): returns
    (rois [N, post_nms_top_n, 4], roi_probs [N, post_nms_top_n, 1],
    rois_num [N]) padded."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    probs = helper.create_variable_for_type_inference(scores.dtype)
    num = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "generate_proposals",
        inputs={
            "Scores": [scores],
            "BboxDeltas": [bbox_deltas],
            "ImInfo": [im_info],
            "Anchors": [anchors],
            "Variances": [variances],
        },
        outputs={"RpnRois": [rois], "RpnRoiProbs": [probs], "RpnRoisNum": [num]},
        attrs={
            "pre_nms_topN": pre_nms_top_n,
            "post_nms_topN": post_nms_top_n,
            "nms_thresh": nms_thresh,
            "min_size": min_size,
        },
    )
    return rois, probs, num


def rpn_target_assign(
    bbox_pred,
    cls_logits,
    anchor_box,
    anchor_var,
    gt_boxes,
    is_crowd=None,
    im_info=None,
    rpn_batch_size_per_im=256,
    rpn_straddle_thresh=0.0,
    rpn_fg_fraction=0.5,
    rpn_positive_overlap=0.7,
    rpn_negative_overlap=0.3,
    use_random=True,
    gt_num=None,
    name=None,
):
    """RPN target assignment (rpn_target_assign_op.cc).

    Dense re-expression: instead of gathered index lists returns
    (labels [N, A] with 1/0/-1, bbox_targets [N, A, 4],
    bbox_inside_weights [N, A, 4]) — mask losses by label>=0 rather than
    gathering (static shapes).
    """
    helper = LayerHelper("rpn_target_assign", name=name)
    labels = helper.create_variable_for_type_inference("int32")
    tgts = helper.create_variable_for_type_inference(gt_boxes.dtype)
    inw = helper.create_variable_for_type_inference("float32")
    inputs = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes]}
    if gt_num is not None:
        inputs["GtNum"] = [gt_num]
    helper.append_op(
        "rpn_target_assign",
        inputs=inputs,
        outputs={
            "TargetLabel": [labels],
            "TargetBBox": [tgts],
            "BBoxInsideWeight": [inw],
        },
        attrs={
            "rpn_batch_size_per_im": rpn_batch_size_per_im,
            "rpn_fg_fraction": rpn_fg_fraction,
            "rpn_positive_overlap": rpn_positive_overlap,
            "rpn_negative_overlap": rpn_negative_overlap,
        },
    )
    return labels, tgts, inw


def generate_proposal_labels(
    rpn_rois,
    gt_classes,
    is_crowd=None,
    gt_boxes=None,
    im_info=None,
    batch_size_per_im=512,
    fg_fraction=0.25,
    fg_thresh=0.5,
    bg_thresh_hi=0.5,
    bg_thresh_lo=0.0,
    bbox_reg_weights=[0.1, 0.1, 0.2, 0.2],
    class_nums=81,
    use_random=True,
    rois_num=None,
    gt_num=None,
    name=None,
):
    """Second-stage RoI sampling (generate_proposal_labels_op.cc) — dense
    padded contract, see ops/detection_ops.py:_generate_proposal_labels."""
    helper = LayerHelper("generate_proposal_labels", name=name)
    rois = helper.create_variable_for_type_inference(rpn_rois.dtype)
    labels = helper.create_variable_for_type_inference("int32")
    tgts = helper.create_variable_for_type_inference(rpn_rois.dtype)
    inw = helper.create_variable_for_type_inference("float32")
    outw = helper.create_variable_for_type_inference("float32")
    num = helper.create_variable_for_type_inference("int32")
    inputs = {
        "RpnRois": [rpn_rois],
        "GtClasses": [gt_classes],
        "GtBoxes": [gt_boxes],
    }
    if rois_num is not None:
        inputs["RpnRoisNum"] = [rois_num]
    if gt_num is not None:
        inputs["GtNum"] = [gt_num]
    helper.append_op(
        "generate_proposal_labels",
        inputs=inputs,
        outputs={
            "Rois": [rois],
            "LabelsInt32": [labels],
            "BboxTargets": [tgts],
            "BboxInsideWeights": [inw],
            "BboxOutsideWeights": [outw],
            "RoisNum": [num],
        },
        attrs={
            "batch_size_per_im": batch_size_per_im,
            "fg_fraction": fg_fraction,
            "fg_thresh": fg_thresh,
            "bg_thresh_hi": bg_thresh_hi,
            "bg_thresh_lo": bg_thresh_lo,
            "class_nums": class_nums,
            "bbox_reg_weights": list(bbox_reg_weights),
        },
    )
    return rois, labels, tgts, inw, outw, num


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        "bipartite_match",
        inputs={"DistMat": [dist_matrix]},
        outputs={"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [dist]},
        attrs={"match_type": match_type, "dist_threshold": dist_threshold},
    )
    return idx, dist


def target_assign(input, matched_indices, negative_indices=None, mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    w = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "target_assign",
        inputs={"X": [input], "MatchIndices": [matched_indices]},
        outputs={"Out": [out], "OutWeight": [w]},
        attrs={"mismatch_value": mismatch_value},
    )
    return out, w


def mine_hard_examples(
    cls_loss,
    match_indices,
    loc_loss=None,
    match_dist=None,
    neg_pos_ratio=3.0,
    neg_dist_threshold=0.5,
    mining_type="max_negative",
    name=None,
):
    """Dense hard-negative mining: returns (neg_mask [N, P], updated_match
    [N, P]) — see ops/detection_ops.py:_mine_hard_examples."""
    helper = LayerHelper("mine_hard_examples", name=name)
    neg = helper.create_variable_for_type_inference("int32")
    upd = helper.create_variable_for_type_inference("int32")
    inputs = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss]
    if match_dist is not None:
        inputs["MatchDist"] = [match_dist]
    helper.append_op(
        "mine_hard_examples",
        inputs=inputs,
        outputs={"NegMask": [neg], "UpdatedMatchIndices": [upd]},
        attrs={
            "neg_pos_ratio": neg_pos_ratio,
            "neg_dist_threshold": neg_dist_threshold,
        },
    )
    return neg, upd


def anchor_generator(
    input,
    anchor_sizes=[64.0, 128.0, 256.0, 512.0],
    aspect_ratios=[0.5, 1.0, 2.0],
    variance=[0.1, 0.1, 0.2, 0.2],
    stride=[16.0, 16.0],
    offset=0.5,
    name=None,
):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "anchor_generator",
        inputs={"Input": [input]},
        outputs={"Anchors": [anchors], "Variances": [variances]},
        attrs={
            "anchor_sizes": list(anchor_sizes),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "stride": list(stride),
            "offset": offset,
        },
    )
    return anchors, variances


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0, rois_batch=None):
    helper = LayerHelper("roi_pool")
    out = helper.create_variable_for_type_inference(input.dtype)
    argmax = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        "roi_pool",
        inputs=inputs,
        outputs={"Out": [out], "Argmax": [argmax]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def roi_align(
    input,
    rois,
    pooled_height=1,
    pooled_width=1,
    spatial_scale=1.0,
    sampling_ratio=-1,
    rois_batch=None,
):
    helper = LayerHelper("roi_align")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        "roi_align",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "pooled_height": pooled_height,
            "pooled_width": pooled_width,
            "spatial_scale": spatial_scale,
            "sampling_ratio": sampling_ratio,
        },
    )
    return out


def roi_perspective_transform(
    input, rois, transformed_height, transformed_width, spatial_scale=1.0, rois_batch=None
):
    helper = LayerHelper("roi_perspective_transform")
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        inputs["RoisBatch"] = [rois_batch]
    helper.append_op(
        "roi_perspective_transform",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={
            "transformed_height": transformed_height,
            "transformed_width": transformed_width,
            "spatial_scale": spatial_scale,
        },
    )
    return out


def density_prior_box(
    input, image, densities=None, fixed_sizes=None, fixed_ratios=None,
    variance=[0.1, 0.1, 0.2, 0.2], clip=False, steps=[0.0, 0.0], offset=0.5,
    name=None,
):
    """density_prior_box_op.cc: dense multi-scale prior grid per cell."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    variances = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "density_prior_box",
        inputs={"Input": [input], "Image": [image]},
        outputs={"Boxes": [boxes], "Variances": [variances]},
        attrs={
            "densities": list(densities or []),
            "fixed_sizes": list(fixed_sizes or []),
            "fixed_ratios": list(fixed_ratios or []),
            "variances": list(variance),
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def polygon_box_transform(input, name=None):
    """polygon_box_transform_op.cc (EAST-style geometry decode)."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "polygon_box_transform", inputs={"Input": [input]},
        outputs={"Output": [out]},
    )
    return out


def detection_map(detect_res, label, overlap_threshold=0.5, name=None,
                  ap_version="integral", evaluate_difficult=True,
                  accum_key=None):
    """detection_map_op.cc: mAP (host-callback evaluator).
    detect_res: [N, 6] (label, score, box); label: [G, 5] (label, box)
    or [G, 6] (label, difficult, box).  accum_key (evaluator.DetectionMAP
    plumbing): names a persistent host accumulator — the op then returns
    the STREAMING mAP over every batch fed since the last reset."""
    helper = LayerHelper("detection_map", name=name)
    out = helper.create_variable_for_type_inference("float32")
    attrs = {
        "overlap_threshold": float(overlap_threshold),
        "ap_version": str(ap_version),
        "evaluate_difficult": bool(evaluate_difficult),
    }
    op_type = "detection_map"
    if accum_key:
        # the streaming variant is a SIDE-EFFECTING op type: dead-op
        # pruning must never drop an unfetched accumulation and the
        # profiler must never warm-rerun (double-feed) one
        attrs["accum_key"] = str(accum_key)
        op_type = "detection_map_accum"
    helper.append_op(
        op_type,
        inputs={"DetectRes": [detect_res], "Label": [label]},
        outputs={"MAP": [out]},
        attrs=attrs,
    )
    return out


def multi_box_head(
    inputs, image, base_size, num_classes, aspect_ratios, min_ratio=None,
    max_ratio=None, min_sizes=None, max_sizes=None, flip=True, clip=False,
    name=None,
):
    """SSD detection head (the reference's multi_box_head composition):
    per feature map, a 3x3 conv predicts per-prior box offsets and class
    scores; priors come from prior_box on the same map.  Returns
    (mbox_locs [B, P, 4], mbox_confs [B, P, C], boxes [P, 4],
    variances [P, 4])."""
    from . import nn as _nn

    if min_sizes is None:
        # the reference's ratio schedule between min_ratio and max_ratio
        n = len(inputs)
        min_sizes, max_sizes = [], []
        step = max(int((max_ratio - min_ratio) / max(n - 2, 1)), 1)
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        # narrow ranges can yield fewer entries than layers: extend with
        # the last size so every feature map gets a schedule entry
        while len(min_sizes) < n - 1:
            min_sizes.append(min_sizes[-1])
            max_sizes.append(max_sizes[-1])
        min_sizes = [base_size * 0.1] + min_sizes[: n - 1]
        max_sizes = [base_size * 0.2] + max_sizes[: n - 1]

    locs, confs, all_boxes, all_vars = [], [], [], []
    for i, feat in enumerate(inputs):
        # the reference indexes aspect_ratios PER LAYER — a flat list means
        # one ratio per feature map, never "all ratios everywhere"
        ar = aspect_ratios[i]
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        mins = min_sizes[i] if isinstance(min_sizes, (list, tuple)) else min_sizes
        maxs = max_sizes[i] if isinstance(max_sizes, (list, tuple)) else max_sizes
        mins = [mins] if not isinstance(mins, (list, tuple)) else list(mins)
        maxs = [maxs] if not isinstance(maxs, (list, tuple)) else list(maxs)
        boxes, variances = prior_box(
            feat, image, mins, maxs, list(ar), flip=flip, clip=clip
        )
        # priors per cell = boxes.shape[2] after [H, W, P, 4]
        num_priors = int(boxes.shape[2])
        loc = _nn.conv2d(feat, num_priors * 4, 3, padding=1)
        conf = _nn.conv2d(feat, num_priors * num_classes, 3, padding=1)
        # reshape dim 0 = 0 keeps the (dynamic) batch dim as-is
        loc = _nn.transpose(loc, [0, 2, 3, 1])
        loc = _nn.reshape(loc, [0, -1, 4])
        conf = _nn.transpose(conf, [0, 2, 3, 1])
        conf = _nn.reshape(conf, [0, -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        all_boxes.append(_nn.reshape(boxes, [-1, 4]))
        all_vars.append(_nn.reshape(variances, [-1, 4]))

    from .tensor import concat

    return (
        concat(locs, axis=1),
        concat(confs, axis=1),
        concat(all_boxes, axis=0),
        concat(all_vars, axis=0),
    )


__all__ += [
    "density_prior_box",
    "polygon_box_transform",
    "detection_map",
    "multi_box_head",
]
