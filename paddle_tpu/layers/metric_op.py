"""In-graph metric layers (layers/metric_op.py analog)."""

from ..layer_helper import LayerHelper
from . import nn

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64")
    if total is None:
        total = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=2 ** 12 - 1, topk=1,
        slide_steps=1):
    """Streaming in-graph AUC (metric_op.py:81 auc / auc_op.cc): two auc
    ops share the batch histogram work — a GLOBAL accumulator
    (slide_steps=0) and a sliding-window BATCH accumulator over the last
    `slide_steps` batches (auc_op.h statAuc shift register).  Returns
    (auc_out, batch_auc_out, [batch_stat_pos, batch_stat_neg, stat_pos,
    stat_neg]) exactly like the reference.  `topk` is accepted for
    signature parity and unused — the reference layer never reads it
    either (metric_op.py:126)."""
    from ..initializer import Constant
    from .. import unique_name

    # slide_steps=0 means the batch accumulator ALSO accumulates over all
    # batches (reference semantics: batch_auc == global auc then)
    slide_steps = max(0, int(slide_steps))
    helper = LayerHelper("auc")

    def _stat(name, shape):
        v = helper.create_global_variable(
            persistable=True,
            name=unique_name.generate(name),
            shape=shape,
            dtype="float32",
        )
        helper.set_variable_initializer(v, Constant(0.0))
        return v

    nb = num_thresholds + 1
    batch_stat_pos = _stat("auc_batch_stat_pos", [max(1, slide_steps), nb])
    batch_stat_neg = _stat("auc_batch_stat_neg", [max(1, slide_steps), nb])
    stat_pos = _stat("auc_stat_pos", [1, nb])
    stat_neg = _stat("auc_stat_neg", [1, nb])

    def _auc_op(sp, sn, steps):
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "auc",
            inputs={
                "Predict": [input],
                "Label": [label],
                "StatPos": [sp],
                "StatNeg": [sn],
            },
            outputs={
                "AUC": [out],
                "StatPosOut": [sp],
                "StatNegOut": [sn],
            },
            attrs={"num_thresholds": num_thresholds, "curve": curve,
                   "slide_steps": steps},
        )
        out.stop_gradient = True
        return out

    batch_auc_out = _auc_op(batch_stat_pos, batch_stat_neg, slide_steps)
    auc_out = _auc_op(stat_pos, stat_neg, 0)
    return auc_out, batch_auc_out, [
        batch_stat_pos, batch_stat_neg, stat_pos, stat_neg
    ]
