"""In-graph metric layers (layers/metric_op.py analog)."""

from ..layer_helper import LayerHelper
from . import nn

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64")
    if total is None:
        total = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    raise NotImplementedError("auc layer pending (metrics.Auc available host-side)")
