"""In-graph metric layers (layers/metric_op.py analog)."""

from ..layer_helper import LayerHelper
from . import nn

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference("float32")
    if correct is None:
        correct = helper.create_variable_for_type_inference("int64")
    if total is None:
        total = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "accuracy",
        inputs={"Out": [topk_out], "Indices": [topk_indices], "Label": [label]},
        outputs={"Accuracy": [acc_out], "Correct": [correct], "Total": [total]},
    )
    acc_out.stop_gradient = True
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1, slide_steps=1):
    """Streaming in-graph AUC (metric_op.py auc / auc_op.cc): threshold
    buckets accumulate in persistable stat tensors threaded through the
    functionalized scope state; returns (auc_out, [stat_pos, stat_neg])
    like the reference.  curve is ROC or PR; the reference's topk>1 and
    sliding-window modes are not supported (explicit error, never a
    silently-different metric)."""
    from ..initializer import Constant
    from .. import unique_name

    if topk != 1:
        raise NotImplementedError("auc: only topk=1 is supported")
    if slide_steps not in (0, 1):
        raise NotImplementedError(
            "auc: sliding-window accumulation (slide_steps=%r) is not "
            "supported; use slide_steps=0/1 for global accumulation" % slide_steps
        )
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        persistable=True,
        name=unique_name.generate("auc_stat_pos"),
        shape=[num_thresholds + 1],
        dtype="float32",
    )
    stat_neg = helper.create_global_variable(
        persistable=True,
        name=unique_name.generate("auc_stat_neg"),
        shape=[num_thresholds + 1],
        dtype="float32",
    )
    for v in (stat_pos, stat_neg):
        helper.set_variable_initializer(v, Constant(0.0))
    auc_out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "auc",
        inputs={
            "Predict": [input],
            "Label": [label],
            "StatPos": [stat_pos],
            "StatNeg": [stat_neg],
        },
        outputs={
            "AUC": [auc_out],
            "StatPosOut": [stat_pos],
            "StatNegOut": [stat_neg],
        },
        attrs={"num_thresholds": num_thresholds, "curve": curve},
    )
    auc_out.stop_gradient = True
    return auc_out, [stat_pos, stat_neg]
