"""Layer wrappers completing the reference nn.py __all__ surface
(python/paddle/fluid/layers/nn.py) over already-registered lowerings.

Parameters (nce/hsigmoid tables, row_conv filters, bilinear products,
gru_unit gates) are created through LayerHelper exactly like the
hand-written layers; everything else is slot wiring."""


from ..layer_helper import LayerHelper
from . import nn as _nn
from .tensor import concat as _concat

__all__ = [
    "add_position_encoding",
    "affine_channel",
    "affine_grid",
    "autoincreased_step_counter",
    "bilinear_tensor_product",
    "chunk_eval",
    "crf_decoding",
    "crop",
    "ctc_greedy_decoder",
    "dice_loss",
    "dynamic_lstmp",
    "edit_distance",
    "grid_sampler",
    "gru_unit",
    "hash",
    "hsigmoid",
    "im2sequence",
    "image_resize_short",
    "linear_chain_crf",
    "lod_reset",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "lstm_unit",
    "margin_rank_loss",
    "mean_iou",
    "multiplex",
    "nce",
    "pad_constant_like",
    "pool3d",
    "random_crop",
    "rank_loss",
    "row_conv",
    "sequence_enumerate",
    "sequence_expand_as",
    "sequence_scatter",
    "similarity_focus",
    "space_to_depth",
    "warpctc",
]


def _simple(op_type, inputs, n_out=1, dtype=None, attrs=None, out_slots=None):
    helper = LayerHelper(op_type)
    first = next(iter(inputs.values()))[0]
    dtype = dtype or getattr(first, "dtype", "float32")
    outs = [
        helper.create_variable_for_type_inference(dtype) for _ in range(n_out)
    ]
    slots = out_slots or (["Out"] if n_out == 1 else None)
    helper.append_op(
        op_type,
        inputs=inputs,
        outputs={s: [o] for s, o in zip(slots, outs)},
        attrs=attrs or {},
    )
    return outs[0] if n_out == 1 else tuple(outs)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _simple("add_position_encoding", {"X": [input]},
                   attrs={"alpha": float(alpha), "beta": float(beta)})


def affine_channel(x, scale, bias, data_layout="NCHW", name=None):
    return _simple(
        "affine_channel", {"X": [x], "Scale": [scale], "Bias": [bias]},
        attrs={"data_layout": data_layout},
    )


def affine_grid(theta, out_shape, name=None):
    attrs = {}
    inputs = {"Theta": [theta]}
    if isinstance(out_shape, (list, tuple)):
        attrs["output_shape"] = [int(s) for s in out_shape]
    else:
        inputs["OutputShape"] = [out_shape]
    return _simple("affine_grid", inputs, attrs=attrs, out_slots=["Output"])


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Own int64 counter honoring counter_name/begin/step — NOT the LR
    scheduler's shared float32 '@LR_DECAY_COUNTER@' (sharing it would let
    whichever caller ran first clobber the other's begin/step)."""
    from ..initializer import Constant

    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=name, dtype="int64", shape=[1], persistable=True
    )
    if not getattr(counter, "_step_initialized", False):
        # initialize one step back so the first fetch reads `begin`
        helper.set_variable_initializer(counter, Constant(begin - step))
        counter._step_initialized = True
        helper.append_op(
            "increment",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            attrs={"step": float(step)},
        )
        counter.stop_gradient = True
    return counter


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         param_attr=param_attr, bias_attr=bias_attr,
                         act=act)
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=[size, int(x.shape[1]), int(y.shape[1])],
        dtype=x.dtype,
    )
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[1, size], dtype=x.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("bilinear_tensor_product", inputs=inputs,
                     outputs={"Out": [out]})
    return helper.append_activation(out) if act else out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval")
    outs = [helper.create_variable_for_type_inference("float32")
            for _ in range(3)]
    counts = [helper.create_variable_for_type_inference("int64")
              for _ in range(3)]
    inputs = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        inputs["Length"] = [seq_length]
    helper.append_op(
        "chunk_eval",
        inputs=inputs,
        outputs={
            "Precision": [outs[0]],
            "Recall": [outs[1]],
            "F1-Score": [outs[2]],
            "NumInferChunks": [counts[0]],
            "NumLabelChunks": [counts[1]],
            "NumCorrectChunks": [counts[2]],
        },
        attrs={
            "chunk_scheme": chunk_scheme,
            "num_chunk_types": num_chunk_types,
            "excluded_chunk_types": excluded_chunk_types or [],
        },
    )
    return tuple(outs) + tuple(counts)


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the transition table trained by
    linear_chain_crf (looked up by the shared param_attr name)."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    transition = helper.main_program.global_block()._find_var_recursive(
        param_attr.name
    )
    if transition is None:
        raise ValueError(
            "crf_decoding: transition parameter %r not found — train with "
            "linear_chain_crf(param_attr=ParamAttr(name=...)) first"
            % param_attr.name
        )
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [out]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    inputs = {"X": [x]}
    attrs = {}
    if isinstance(shape, (list, tuple)):
        attrs["shape"] = [int(s) for s in shape]
    elif shape is not None:
        inputs["Y"] = [shape]
    if isinstance(offsets, (list, tuple)):
        attrs["offsets"] = [int(o) for o in offsets]
    elif offsets is not None:
        inputs["Offsets"] = [offsets]
    return _simple("crop", inputs, attrs=attrs)


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    helper = LayerHelper("ctc_align", name=name)
    out = helper.create_variable_for_type_inference("int64")
    out_len = helper.create_variable_for_type_inference("int64")
    inputs = {"Input": [input]}
    if input_length is not None:
        inputs["InputLength"] = [input_length]
    helper.append_op(
        "ctc_align", inputs=inputs,
        outputs={"Output": [out], "OutputLength": [out_len]},
        attrs={"blank": int(blank), "merge_repeated": True},
    )
    out.seq_len = out_len
    return out


def dice_loss(input, label, epsilon=1e-5):
    """Reference composition: mean over the batch of
    1 - 2|X∩Y| / (|X|+|Y|+eps), the sums taken over ALL non-batch dims
    (one ratio per sample — mean-of-per-pixel-ratios would diverge for
    segmentation inputs).  Scalar loss fit for minimize()."""
    label = _nn.one_hot(label, int(input.shape[-1]))
    reduce_dims = list(range(1, len(input.shape)))
    intersect = _nn.reduce_sum(
        _nn.elementwise_mul(input, label), dim=reduce_dims
    )
    denom = _nn.elementwise_add(
        _nn.reduce_sum(input, dim=reduce_dims),
        _nn.reduce_sum(label, dim=reduce_dims),
    )
    num = _nn.scale(intersect, scale=2.0)
    den = _nn.scale(denom, scale=1.0, bias=float(epsilon))
    per_sample = _nn.scale(
        _nn.elementwise_div(num, den), scale=-1.0, bias=1.0
    )
    return _nn.reduce_mean(per_sample)


def dynamic_lstmp(input, size, proj_size, seq_len=None, h0=None, c0=None,
                  param_attr=None, bias_attr=None, is_reverse=False,
                  name=None):
    """LSTM with recurrent projection (lstmp_op): input is the
    pre-projected [B, T, 4*size] gates (use an fc, as dynamic_lstm)."""
    helper = LayerHelper("lstmp", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    hidden = size // 4
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[proj_size, size], dtype=input.dtype
    )
    proj_w = helper.create_parameter(
        attr=None, shape=[hidden, proj_size], dtype=input.dtype
    )
    inputs = {"Input": [input], "Weight": [w], "ProjWeight": [proj_w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[size], dtype=input.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    if h0 is not None:
        inputs["H0"] = [h0]
    if c0 is not None:
        inputs["C0"] = [c0]
    proj = helper.create_variable_for_type_inference(input.dtype)
    cell = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "lstmp", inputs=inputs,
        outputs={"Projection": [proj], "Cell": [cell]},
        attrs={"is_reverse": is_reverse},
    )
    return proj, cell


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None):
    helper = LayerHelper("edit_distance")
    out = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    inputs = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        inputs["HypsLength"] = [input_length]
    if label_length is not None:
        inputs["RefsLength"] = [label_length]
    helper.append_op(
        "edit_distance", inputs=inputs,
        outputs={"Out": [out], "SequenceNum": [seq_num]},
        attrs={"normalized": normalized},
    )
    return out, seq_num


def grid_sampler(x, grid, name=None):
    return _simple("grid_sampler", {"X": [x], "Grid": [grid]},
                   out_slots=["Output"])


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    hid = size // 3
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[hid, size], dtype=input.dtype
    )
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[size], dtype=input.dtype,
            is_bias=True,
        )
        inputs["Bias"] = [b]
    gate = helper.create_variable_for_type_inference(input.dtype)
    reset = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gru_unit", inputs=inputs,
        outputs={"Gate": [gate], "ResetHiddenPrev": [reset], "Hidden": [out]},
        attrs={"activation": activation, "gate_activation": gate_activation},
    )
    return out, reset, gate


def hash(input, hash_size, num_hash=1, name=None):
    return _simple("hash", {"X": [input]}, dtype="int32",
                   attrs={"mod_by": int(hash_size), "num_hash": int(num_hash)})


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    helper = LayerHelper("hierarchical_sigmoid", param_attr=param_attr,
                         bias_attr=bias_attr)
    dim = int(input.shape[1])
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_classes - 1, dim],
        dtype=input.dtype,
    )
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_classes - 1, 1],
            dtype=input.dtype, is_bias=True,
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "hierarchical_sigmoid", inputs=inputs,
        outputs={"Out": [out], "PreOut": [pre]},
        attrs={"num_classes": int(num_classes)},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    def _pair(v):
        return [v, v] if isinstance(v, int) else [int(i) for i in v]

    pad = _pair(padding)
    if len(pad) == 2:
        pad = pad + pad
    return _simple(
        "im2sequence", {"X": [input]},
        attrs={"kernels": _pair(filter_size), "strides": _pair(stride),
               "paddings": pad},
    )


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORTER spatial edge equals out_short_len (reference
    nn.image_resize_short composition)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    if h < w:
        oh, ow = out_short_len, int(round(w * out_short_len / h))
    else:
        oh, ow = int(round(h * out_short_len / w)), out_short_len
    return _nn.image_resize(input, out_shape=[oh, ow], resample=resample)


def linear_chain_crf(input, label, param_attr=None, length=None):
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    n_tags = int(input.shape[-1])
    transition = helper.create_parameter(
        attr=helper.param_attr, shape=[n_tags + 2, n_tags],
        dtype=input.dtype,
    )
    inputs = {"Emission": [input], "Transition": [transition],
              "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    outs = [helper.create_variable_for_type_inference(input.dtype)
            for _ in range(4)]
    helper.append_op(
        "linear_chain_crf", inputs=inputs,
        outputs={"Alpha": [outs[0]], "EmissionExps": [outs[1]],
                 "TransitionExps": [outs[2]], "LogLikelihood": [outs[3]]},
    )
    return outs[3]


def lod_reset(x, y=None, target_lod=None):
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    return _simple("lod_reset", inputs,
                   attrs={"target_lod": target_lod or []})


def _logical(op_type, x, y=None, out=None, name=None):
    inputs = {"X": [x]}
    if y is not None:
        inputs["Y"] = [y]
    if out is not None:
        helper = LayerHelper(op_type, name=name)
        helper.append_op(op_type, inputs=inputs, outputs={"Out": [out]})
        return out
    return _simple(op_type, inputs, dtype="bool")


def logical_and(x, y, out=None, name=None):
    return _logical("logical_and", x, y, out, name)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out, name)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out, name)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out, name)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step (lstm_unit_op): gates come from an fc over
    [x_t, h_prev] like the reference composition."""
    concat = _concat([x_t, hidden_t_prev], axis=1)
    size = 4 * int(cell_t_prev.shape[1])
    gates = _nn.fc(concat, size=size, param_attr=param_attr,
                   bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", name=name)
    c = helper.create_variable_for_type_inference(x_t.dtype)
    h = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        "lstm_unit", inputs={"X": [gates], "C_prev": [cell_t_prev]},
        outputs={"C": [c], "H": [h]},
        attrs={"forget_bias": float(forget_bias)},
    )
    return h, c


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        "margin_rank_loss",
        inputs={"Label": [label], "X1": [left], "X2": [right]},
        outputs={"Out": [out], "Activated": [act]},
        attrs={"margin": float(margin)},
    )
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("int32")
    correct = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "mean_iou",
        inputs={"Predictions": [input], "Labels": [label]},
        outputs={"OutMeanIou": [miou], "OutWrong": [wrong],
                 "OutCorrect": [correct]},
        attrs={"num_classes": int(num_classes)},
    )
    return miou, wrong, correct


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(
        "multiplex", inputs={"X": list(inputs), "Ids": [index]},
        outputs={"Out": [out]},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None):
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr)
    dim = int(input.shape[1])
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype,
    )
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight]
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[num_total_classes, 1],
            dtype=input.dtype, is_bias=True,
        )
        inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    sl = helper.create_variable_for_type_inference(input.dtype)
    sll = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "nce", inputs=inputs,
        outputs={"Cost": [cost], "SampleLogits": [sl], "SampleLabels": [sll]},
        attrs={"num_total_classes": int(num_total_classes),
               "num_neg_samples": int(num_neg_samples or 10)},
    )
    return cost


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple("pad_constant_like", {"X": [x], "Y": [y]},
                   attrs={"pad_value": float(pad_value)})


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None):
    def _trip(v):
        return [v, v, v] if isinstance(v, int) else [int(i) for i in v]

    return _simple(
        "pool3d", {"X": [input]},
        attrs={"ksize": _trip(pool_size), "pooling_type": pool_type,
               "strides": _trip(pool_stride), "paddings": _trip(pool_padding),
               "global_pooling": bool(global_pooling)},
    )


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    seed_out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "random_crop", inputs={"X": [x]},
        outputs={"Out": [out], "SeedOut": [seed_out]},
        attrs={"shape": [int(s) for s in shape],
               "startup_seed": int(seed or 0)},
    )
    return out


def rank_loss(label, left, right, name=None):
    return _simple("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]})


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    filt = helper.create_parameter(
        attr=helper.param_attr,
        shape=[future_context_size + 1, int(input.shape[-1])],
        dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("row_conv", inputs={"X": [input], "Filter": [filt]},
                     outputs={"Out": [out]})
    return helper.append_activation(out) if act else out


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    return _simple("sequence_enumerate", {"X": [input]}, dtype=input.dtype,
                   attrs={"win_size": int(win_size),
                          "pad_value": int(pad_value)})


def sequence_expand_as(x, y, name=None):
    return _simple("sequence_expand_as", {"X": [x], "Y": [y]})


def sequence_scatter(input, index, updates, name=None):
    return _simple("sequence_scatter",
                   {"X": [input], "Ids": [index], "Updates": [updates]})


def similarity_focus(input, axis, indexes, name=None):
    return _simple("similarity_focus", {"X": [input]},
                   attrs={"axis": int(axis),
                          "indexes": [int(i) for i in indexes]})


def space_to_depth(x, blocksize, name=None):
    return _simple("space_to_depth", {"X": [x]},
                   attrs={"blocksize": int(blocksize)})


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(input.dtype)
    grad = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    helper.append_op(
        "warpctc", inputs=inputs,
        outputs={"Loss": [loss], "WarpCTCGrad": [grad]},
        attrs={"blank": int(blank), "norm_by_times": bool(norm_by_times)},
    )
    return loss
