"""Operator overloading on Variables (math_op_patch.py analog)."""

import numpy as np

from .. import framework
from ..layer_helper import LayerHelper


def _create_out(helper, dtype):
    return helper.create_variable_for_type_inference(dtype)


def scale(var, scale_val=1.0, bias=0.0):
    helper = LayerHelper("scale")
    out = _create_out(helper, var.dtype)
    helper.append_op(
        "scale",
        inputs={"X": [var]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale_val), "bias": float(bias)},
    )
    return out


def _scalar_elementwise(var, op, scalar, reverse):
    if op == "elementwise_add":
        return scale(var, 1.0, scalar)
    if op == "elementwise_sub":
        if reverse:
            return scale(var, -1.0, scalar)
        return scale(var, 1.0, -scalar)
    if op == "elementwise_mul":
        return scale(var, scalar, 0.0)
    if op == "elementwise_div" and not reverse:
        return scale(var, 1.0 / scalar, 0.0)
    # fall through: build constant tensor
    return None


def binary(var, other, op, reverse=False):
    helper = LayerHelper(op)
    if isinstance(other, (np.integer, np.floating)):
        other = float(other)
    if isinstance(other, (int, float)):
        if op in ("elementwise_add", "elementwise_sub", "elementwise_mul") or (
            op == "elementwise_div" and not reverse
        ):
            out = _scalar_elementwise(var, op, float(other), reverse)
            if out is not None:
                return out
        # materialize a scalar tensor
        from . import tensor as tensor_layers

        other = tensor_layers.fill_constant([1], var.dtype, float(other))
    x, y = (other, var) if reverse else (var, other)
    compare = op in (
        "less_than",
        "less_equal",
        "greater_than",
        "greater_equal",
        "equal",
        "not_equal",
    )
    out = _create_out(helper, "bool" if compare else var.dtype)
    helper.append_op(op, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={"axis": -1})
    return out
