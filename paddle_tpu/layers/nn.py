"""Neural-net layers (python/paddle/fluid/layers/nn.py analog).

Each function appends ops to the current program block via LayerHelper —
same graph-building contract as the reference (nn.py:174 fc, :283 embedding,
:1524 conv2d, :2290 batch_norm ...), with lowerings that compile to
MXU-friendly XLA ops.
"""

import numpy as np

from .. import framework, unique_name
from ..framework import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "conv3d_transpose",
    "data_norm",

    "fused_attention",
    "slot_cache_write",
    "rotary_embed",
    "log_loss",
    "beam_search",
    "beam_search_decode",
    "fc",
    "embedding",
    "conv2d",
    "conv3d",
    "conv2d_transpose",
    "depthwise_conv2d",
    "pool2d",
    "adaptive_pool2d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "instance_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "huber_loss",
    "label_smooth",
    "mean",
    "mul",
    "matmul",
    "dot",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "reshape",
    "transpose",
    "flatten",
    "squeeze",
    "unsqueeze",
    "split",
    "slice",
    "expand",
    "stack",
    "unstack",
    "topk",
    "one_hot",
    "l2_normalize",
    "clip",
    "clip_by_norm",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "pad",
    "pad2d",
    "prelu",
    "maxout",
    "relu",
    "lrn",
    "resize_bilinear",
    "resize_nearest",
    "image_resize",
    "gather",
    "gather_nd",
    "scatter",
    "shape",
    "gaussian_random",
    "uniform_random",
    "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like",
    "sampling_id",
    "dynamic_lstm",
    "dynamic_gru",
    "lstm",
    "gru",
    "sum",
    "cos_sim",
    "pow",
    "scale",
    "hard_sigmoid",
    "swish",
    "leaky_relu",
    "elu",
    "relu6",
    "pixel_shuffle",
    "where",
    "cond_take",
    "unfold",
    "increment",
    "cumsum",
]


def _helper_out(helper, dtype=None):
    return helper.create_variable_for_type_inference(dtype or helper.input_dtype())


def _simple(op_type, x, attrs=None, name=None, out_dtype=None, x_slot="X", out_slot="Out"):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    helper.append_op(
        op_type, inputs={x_slot: [x]}, outputs={out_slot: [out]}, attrs=attrs or {}
    )
    return out


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    is_test=False,
    name=None,
):
    """Fully-connected (nn.py:174 parity): per input a mul op, summed, bias,
    activation. Lowered to one MXU matmul per input."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr_ in zip(
        helper.multiple_input(), helper.multiple_param_attr(len(helper.multiple_input()))
    ):
        input_shape = input_var.shape
        param_shape = [
            int(np.prod(input_shape[num_flatten_dims:]))
        ] + [size]
        w = helper.create_parameter(
            attr=param_attr_, shape=param_shape, dtype=dtype, is_bias=False
        )
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op(
            "mul",
            inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, dim_start=num_flatten_dims)
    return helper.append_activation(pre_act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
):
    """Embedding lookup (nn.py:283). is_sparse/is_distributed are accepted
    for API parity; on TPU the lookup compiles to a gather and the gradient
    to a scatter-add (the SelectedRows path is unnecessary under XLA)."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False
    )
    tmp = helper.create_variable_for_type_inference(dtype)
    padding_idx = (
        -1 if padding_idx is None else padding_idx if padding_idx >= 0 else size[0] + padding_idx
    )
    helper.append_op(
        "lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [tmp]},
        attrs={
            "padding_idx": padding_idx,
            "is_sparse": bool(is_sparse),
            # consumed by DistributeTranspiler._handle_distributed_lookup:
            # rows shard over pservers, forward becomes a prefetch op
            "is_distributed": bool(is_distributed),
        },
    )
    return tmp


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    """conv2d (nn.py:1524). use_cudnn accepted for parity; lowering always
    targets the MXU via lax.conv_general_dilated."""
    helper = LayerHelper("conv2d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        attr=helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=Normal(0.0, std),
    )
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def depthwise_conv2d(input, num_filters, filter_size, **kwargs):
    kwargs["groups"] = input.shape[1]
    return conv2d(input, num_filters, filter_size, **kwargs)


def conv3d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    helper = LayerHelper("conv3d", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1

    def _trip(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    filter_size, stride, padding, dilation = map(
        _trip, (filter_size, stride, padding, dilation)
    )
    filter_shape = [num_filters, num_channels // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv2d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    if filter_size is None:
        h, w_ = input.shape[2], input.shape[3]
        oh, ow = output_size if isinstance(output_size, (list, tuple)) else (output_size, output_size)
        filter_size = [
            oh - (h - 1) * stride[0] + 2 * padding[0],
            ow - (w_ - 1) * stride[1] + 2 * padding[1],
        ]
    elif isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    use_cudnn=True,
    ceil_mode=False,
    exclusive=True,
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    helper.append_op(
        "pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        },
    )
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "adaptive_pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"ksize": list(pool_size), "pooling_type": pool_type},
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
    use_global_stats=False,
):
    """batch_norm (nn.py:2290): creates scale/bias params + persistable
    moving mean/variance; training mode updates the moving stats in the same
    compiled step (functionalized in-place outputs)."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = helper.input_dtype()
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    param_shape = [channels]

    scale = helper.create_parameter(
        attr=helper.param_attr,
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(1.0),
    )
    bias = helper.create_parameter(
        attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name, trainable=False),
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(0.0),
    )
    mean.stop_gradient = True
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name, trainable=False),
        shape=param_shape,
        dtype=dtype,
        default_initializer=Constant(1.0),
    )
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        "batch_norm",
        inputs={
            "X": [input],
            "Scale": [scale],
            "Bias": [bias],
            "Mean": [mean],
            "Variance": [variance],
        },
        outputs={
            "Y": [out],
            "MeanOut": [mean],
            "VarianceOut": [variance],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_variance],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", **locals())
    dtype = helper.input_dtype()
    param_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=param_shape,
            dtype=dtype,
            default_initializer=Constant(1.0),
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=param_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def group_norm(
    input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None
):
    helper = LayerHelper("group_norm", **locals())
    dtype = helper.input_dtype()
    channels = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        s = helper.create_parameter(
            attr=helper.param_attr,
            shape=[channels],
            dtype=dtype,
            default_initializer=Constant(1.0),
        )
        inputs["Scale"] = [s]
    if bias_attr is not False:
        b = helper.create_parameter(
            attr=helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype)
    mean_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var_out = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "group_norm",
        inputs=inputs,
        outputs={"Y": [out], "Mean": [mean_out], "Variance": [var_out]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    helper = LayerHelper("instance_norm", **locals())
    dtype = helper.input_dtype()
    channels = input.shape[1]
    inputs = {"X": [input]}
    s = helper.create_parameter(
        attr=helper.param_attr,
        shape=[channels],
        dtype=dtype,
        default_initializer=Constant(1.0),
    )
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True
    )
    inputs["Scale"], inputs["Bias"] = [s], [b]
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "instance_norm", inputs=inputs, outputs={"Y": [out]}, attrs={"epsilon": epsilon}
    )
    return out


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed if seed is not None else 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def softmax(input, use_cudnn=True, name=None, axis=-1):
    return _simple("softmax", input, {"axis": axis}, name)


def log_softmax(input, axis=-1, name=None):
    return _simple("log_softmax", input, {"axis": axis}, name)


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index},
    )
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_variable_for_type_inference(x.dtype)
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        "smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": [out], "Diff": [diff]},
        attrs={"sigma": sigma or 1.0},
    )
    return out


def huber_loss(input, label, delta):
    helper = LayerHelper("huber_loss")
    residual = helper.create_variable_for_type_inference(input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "huber_loss",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out], "Residual": [residual]},
        attrs={"delta": delta},
    )
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        "label_smooth", inputs=inputs, outputs={"Out": [out]}, attrs={"epsilon": epsilon}
    )
    return out


def mean(x, name=None):
    return _simple("mean", x, name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": float(alpha)},
    )
    return out


def dot(x, y, name=None):
    helper = LayerHelper("dot", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("dot", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def _reduce(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        attrs = {
            "dim": dim if isinstance(dim, (list, tuple)) else [dim],
            "keep_dim": keep_dim,
            "reduce_all": False,
        }
    helper.append_op(op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out) if act else out


def transpose(x, perm, name=None):
    return _simple("transpose2", x, {"axis": list(perm)}, name)


def flatten(x, axis=1, name=None):
    return _simple("flatten2", x, {"axis": axis}, name)


def squeeze(input, axes, name=None):
    return _simple("squeeze2", input, {"axes": list(axes)}, name)


def unsqueeze(input, axes, name=None):
    return _simple("unsqueeze2", input, {"axes": list(axes)}, name)


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in range(num or len(sections))
    ]
    helper.append_op(
        "split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "num": num, "sections": sections},
    )
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def expand(x, expand_times, name=None):
    return _simple("expand", x, {"expand_times": list(expand_times)}, name)


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        "stack", inputs={"X": x}, outputs={"Y": [out]}, attrs={"axis": axis}
    )
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(num)]
    helper.append_op(
        "unstack", inputs={"X": [x]}, outputs={"Y": outs}, attrs={"axis": axis}
    )
    return outs


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "one_hot", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"depth": depth}
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "l2_normalize",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def clip(x, min, max, name=None):
    return _simple("clip", x, {"min": float(min), "max": float(max)}, name)


def clip_by_norm(x, max_norm, name=None):
    return _simple("clip_by_norm", x, {"max_norm": float(max_norm)}, name)


def _elementwise(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    if act:
        helper.kwargs["act"] = act
        return helper.append_activation(out)
    return out


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise("elementwise_pow", x, y, axis, act, name)


def pad(x, paddings, pad_value=0.0, name=None):
    return _simple("pad", x, {"paddings": list(paddings), "pad_value": pad_value}, name)


def pad2d(input, paddings=[0, 0, 0, 0], mode="constant", pad_value=0.0, data_format="NCHW", name=None):
    return _simple(
        "pad2d",
        input,
        {"paddings": list(paddings), "mode": mode, "pad_value": pad_value, "data_format": data_format},
        name,
    )


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    alpha_shape = [1]
    if mode == "channel":
        alpha_shape = [x.shape[1]]
    elif mode == "element":
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        attr=helper.param_attr,
        shape=alpha_shape,
        dtype="float32",
        default_initializer=Constant(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


def maxout(x, groups, name=None):
    helper = LayerHelper("maxout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "maxout", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"groups": groups}
    )
    return out


def relu(x, name=None):
    return _simple("relu", x, name=name)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "lrn",
        inputs={"X": [input]},
        outputs={"Out": [out], "MidOut": [mid]},
        attrs={"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def image_resize(input, out_shape=None, scale=None, name=None, resample="BILINEAR"):
    op = "bilinear_interp" if resample.upper() == "BILINEAR" else "nearest_interp"
    if out_shape is None:
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    return _simple(op, input, {"out_h": out_shape[0], "out_w": out_shape[1]}, name)


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "BILINEAR")


def resize_nearest(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, "NEAREST")


def gather(input, index, overwrite=True):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gather", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gather_nd", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]}
    )
    return out


def scatter(input, index, updates, name=None, overwrite=True):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("shape", inputs={"Input": [input]}, outputs={"Out": [out]})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gaussian_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "mean": mean, "std": std, "seed": seed, "dtype": dtype},
    )
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "uniform_random",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "min": min, "max": max, "seed": seed, "dtype": dtype},
    )
    return out


def uniform_random_batch_size_like(
    input, shape, dtype="float32", input_dim_idx=0, output_dim_idx=0, min=-1.0, max=1.0, seed=0
):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "uniform_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
            "min": min,
            "max": max,
            "seed": seed,
            "dtype": dtype,
        },
    )
    return out


def gaussian_random_batch_size_like(
    input, shape, input_dim_idx=0, output_dim_idx=0, mean=0.0, std=1.0, seed=0, dtype="float32"
):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gaussian_random_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
            "mean": mean,
            "std": std,
            "seed": seed,
            "dtype": dtype,
        },
    )
    return out


def sum(x):
    helper = LayerHelper("sum")
    if isinstance(x, Variable):
        x = [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("sum", inputs={"X": x}, outputs={"Out": [out]})
    return out


def cos_sim(X, Y):
    helper = LayerHelper("cos_sim")
    xn = l2_normalize(X, axis=-1)
    yn = l2_normalize(Y, axis=-1)
    return reduce_sum(elementwise_mul(xn, yn), dim=-1, keep_dim=True)


def pow(x, factor=1.0, name=None):
    return _simple("pow", x, {"factor": float(factor)}, name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={
            "scale": float(scale),
            "bias": float(bias),
            "bias_after_scale": bias_after_scale,
        },
    )
    if act:
        helper.kwargs["act"] = act
        return helper.append_activation(out)
    return out


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple("hard_sigmoid", x, {"slope": slope, "offset": offset}, name)


def swish(x, beta=1.0, name=None):
    return _simple("swish", x, {"beta": beta}, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _simple("leaky_relu", x, {"alpha": alpha}, name)


def elu(x, alpha=1.0, name=None):
    return _simple("elu", x, {"alpha": alpha}, name)


def relu6(x, threshold=6.0, name=None):
    return _simple("relu6", x, {"threshold": threshold}, name)


def pixel_shuffle(x, upscale_factor):
    return _simple("pixel_shuffle", x, {"upscale_factor": upscale_factor})


def where(condition, x=None, y=None):
    helper = LayerHelper("where")
    if x is None:
        out = helper.create_variable_for_type_inference("int64")
        helper.append_op(
            "where_index", inputs={"Condition": [condition]}, outputs={"Out": [out]}
        )
        return out
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def cond_take(x, mask):
    """Masked take with static shapes: values of ``x`` where ``mask`` is
    true, stably compacted to the front of a zero-padded full-size buffer,
    plus the true count (the TPU-shaped CondOp/masked-select)."""
    helper = LayerHelper("cond_take")
    out = helper.create_variable_for_type_inference(x.dtype)
    count = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "cond_take",
        inputs={"X": [x], "Mask": [mask]},
        outputs={"Out": [out], "Count": [count]},
    )
    return out, count


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col as a layer: NCHW -> [N, C*kh*kw, L] sliding patches
    (unfold_op; the host im2col of the reference's math/im2col.h becomes
    one fused XLA gather)."""
    def _pair(v):
        return [v, v] if isinstance(v, int) else [int(i) for i in v]

    helper = LayerHelper("unfold", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "unfold",
        inputs={"X": [x]},
        outputs={"Y": [out]},
        attrs={
            "kernel_sizes": _pair(kernel_sizes),
            "strides": _pair(strides),
            "paddings": _pair(paddings),
            "dilations": _pair(dilations),
        },
    )
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": float(value)}
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False):
    return _simple(
        "cumsum", x, {"axis": axis, "exclusive": exclusive, "reverse": reverse}
    )


# ---------------------------------------------------------------------------
# recurrent layers (padded, scan-backed — nn.py dynamic_lstm/dynamic_gru
# re-expressed for static shapes; see ops/nn_ops.py padded_lstm)
# ---------------------------------------------------------------------------
def dynamic_lstm(
    input,
    size,
    h_0=None,
    c_0=None,
    param_attr=None,
    bias_attr=None,
    use_peepholes=False,
    is_reverse=False,
    gate_activation="sigmoid",
    cell_activation="tanh",
    candidate_activation="tanh",
    dtype="float32",
    name=None,
    seq_len=None,
):
    """LSTM over padded [batch, time, 4*hidden] input (projection done by a
    preceding fc, as in the reference's dynamic_lstm contract nn.py:443).
    Returns (hidden [B,T,H], cell-last [B,H])."""
    helper = LayerHelper("lstm", **locals())
    hidden_size = size // 4
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[hidden_size, 4 * hidden_size], dtype=dtype
    )
    b = helper.create_parameter(
        attr=helper.bias_attr, shape=[4 * hidden_size], dtype=dtype, is_bias=True
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [w], "Bias": [b]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if c_0 is not None:
        inputs["C0"] = [c_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        "padded_lstm",
        inputs=inputs,
        outputs={"Hidden": [hidden], "LastH": [last_h], "LastC": [last_c]},
        attrs={"is_reverse": is_reverse},
    )
    return hidden, last_c


def lstm(input, size, **kwargs):
    return dynamic_lstm(input, size, **kwargs)


def dynamic_gru(
    input,
    size,
    param_attr=None,
    bias_attr=None,
    is_reverse=False,
    h_0=None,
    dtype="float32",
    name=None,
    seq_len=None,
):
    """GRU over padded [batch, time, 3*hidden] projected input."""
    helper = LayerHelper("gru", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=[size, 3 * size], dtype=dtype
    )
    hidden = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input], "Weight": [w]}
    if h_0 is not None:
        inputs["H0"] = [h_0]
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op(
        "padded_gru",
        inputs=inputs,
        outputs={"Hidden": [hidden], "LastH": [last_h]},
        attrs={"is_reverse": is_reverse},
    )
    return hidden


def gru(input, size, **kwargs):
    return dynamic_gru(input, size, **kwargs)


def beam_search(
    pre_ids,
    pre_scores,
    ids,
    scores,
    beam_size,
    end_id,
    level=0,
    is_accumulated=True,
    name=None,
    return_parent_idx=False,
):
    """One beam-search step (layers/nn.py:3174 analog, padded-batch form).

    Contract differs from the LoD reference: `scores` must be rank-3
    [batch, beam, vocab] next-token log-probs (already accumulated with the
    hypothesis history when is_accumulated=True, the default); `pre_ids` /
    `pre_scores` are [batch, beam].  Selects the top `beam_size`
    continuations over beam*vocab per batch row.
    Returns (selected_ids, selected_scores[, parent_idx]), each
    [batch, beam]."""
    helper = LayerHelper("beam_search", **locals())
    sel_ids = helper.create_variable_for_type_inference("int32")
    sel_scores = helper.create_variable_for_type_inference("float32")
    parent_idx = helper.create_variable_for_type_inference("int32")
    inputs = {"pre_ids": [pre_ids], "pre_scores": [pre_scores], "scores": [scores]}
    if ids is not None:
        inputs["ids"] = [ids]
    helper.append_op(
        "beam_search",
        inputs=inputs,
        outputs={
            "selected_ids": [sel_ids],
            "selected_scores": [sel_scores],
            "parent_idx": [parent_idx],
        },
        attrs={"beam_size": beam_size, "end_id": end_id, "level": level,
               "is_accumulated": is_accumulated},
    )
    if return_parent_idx:
        return sel_ids, sel_scores, parent_idx
    return sel_ids, sel_scores


def beam_search_decode(ids, scores, parent_idx=None, beam_size=None, end_id=0, name=None):
    """Backtrack per-step beam choices into full hypotheses
    (layers/nn.py beam_search_decode analog). `ids`/`scores`/`parent_idx`
    are stacked per-step tensors [T, batch, beam]."""
    helper = LayerHelper("beam_search_decode", **locals())
    sent_ids = helper.create_variable_for_type_inference("int32")
    sent_scores = helper.create_variable_for_type_inference("float32")
    inputs = {"Ids": [ids], "Scores": [scores]}
    if parent_idx is not None:
        inputs["ParentIdx"] = [parent_idx]
    helper.append_op(
        "beam_search_decode",
        inputs=inputs,
        outputs={"SentenceIds": [sent_ids], "SentenceScores": [sent_scores]},
        attrs={"end_id": end_id},
    )
    return sent_ids, sent_scores


def log_loss(input, label, epsilon=1e-4, name=None):
    """Negative-log-likelihood of a probability (log_loss_op.cc)."""
    helper = LayerHelper("log_loss", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "log_loss",
        inputs={"Predicted": [input], "Labels": [label]},
        outputs={"Loss": [out]},
        attrs={"epsilon": epsilon},
    )
    return out


def fused_attention(q, k, v, causal=False, scale=None, bias=None,
                    window=0, segment_ids=None, qstart=None, name=None):
    """Fused scaled-dot-product attention over [batch, heads, T, d]
    (flash-attention kernel under FLAGS_use_pallas).  bias: optional
    additive key-padding bias, rank-1 in the key axis ([B, Tk] or
    [B, 1, 1, Tk]) — covers padding masks without a [Tq, Tk] tensor;
    combine with causal=True for decoder self-attention.  window > 0
    (requires causal): sliding-window local attention — each query
    attends only the last `window` positions, and fully-out-of-window
    blocks are skipped in the flash kernels.  segment_ids: optional
    [B, T] int ids from sequence packing (reader.packing) — attention
    stays within each packed segment (ids compared on the fly, no
    [T, T] mask tensor; rides the flash kernels under FLAGS_use_pallas
    as two extra rank-1 operands, dense-XLA otherwise).  qstart:
    optional [1] int var (chunked KV-cached decode): query i sits at
    GLOBAL position qstart + i while keys sit at their cache indices —
    causal masking applies in global positions and Tq may differ from
    Tk (requires causal=True).  A [batch] qstart keeps PER-ROW offsets
    (the continuous-batching ragged step: each serving slot gets its
    own causal cutoff inside one dispatch; dense-XLA path)."""
    window = int(window)
    if window < 0:
        raise ValueError("fused_attention: window must be >= 0")
    if window and not causal:
        raise ValueError("fused_attention: window requires causal=True")
    if qstart is not None and not causal:
        raise ValueError("fused_attention: qstart requires causal=True "
                         "(it defines the global causal cutoffs)")
    helper = LayerHelper("fused_attention", **locals())
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    if segment_ids is not None:
        inputs["SegmentIds"] = [segment_ids]
    if qstart is not None:
        inputs["QStart"] = [qstart]
    helper.append_op(
        "fused_attention",
        inputs=inputs,
        outputs={"Out": [out]},
        attrs={"causal": causal, "scale": scale, "window": int(window)},
    )
    return out


def slot_cache_write(cache, new, pos, width, name=None):
    """Per-row ragged KV-cache write (continuous-batching serving step):
    row b of `new` [B, H, W, D] lands in `cache` [B, H, T, D] at time
    indices pos[b]..pos[b]+width[b]-1; columns beyond width[b] (or past
    the cache) are dropped, never clamped.  Returns the updated
    full-length cache tensor (the caller assigns it back to the
    persistable var, as with seq_cache_write)."""
    helper = LayerHelper("slot_cache_write", **locals())
    out = helper.create_variable_for_type_inference(cache.dtype)
    helper.append_op(
        "slot_cache_write",
        inputs={"Cache": [cache], "New": [new], "Pos": [pos],
                "Width": [width]},
        outputs={"Out": [out]},
    )
    return out


def rotary_embed(x, pos=None, base=10000.0, name=None):
    """Rotary position embedding over per-head projections [B, H, T, Dh]
    (rotate-half).  pos: optional int positions [T] — the KV-cached
    decode path passes the current position so cached keys are stored
    pre-rotated; default arange(T).  A [B, T] pos keeps per-row
    positions (ragged serving step)."""
    helper = LayerHelper("rotary_embed", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if pos is not None:
        inputs["Pos"] = [pos]
    helper.append_op("rotary_embed", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"base": base})
    return out


def conv3d_transpose(
    input,
    num_filters,
    output_size=None,
    filter_size=None,
    padding=0,
    stride=1,
    dilation=1,
    groups=None,
    param_attr=None,
    bias_attr=None,
    use_cudnn=True,
    act=None,
    name=None,
):
    """conv3d_transpose (nn.py conv3d_transpose parity): NCDHW transposed
    convolution (ops/nn_ops.py _conv3d_transpose)."""
    helper = LayerHelper("conv3d_transpose", **locals())
    dtype = helper.input_dtype()
    num_channels = input.shape[1]
    groups = groups or 1

    def _trip(v):
        return [v] * 3 if isinstance(v, int) else list(v)

    stride, padding, dilation = map(_trip, (stride, padding, dilation))
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv3d_transpose needs filter_size or output_size"
            )
        output_size = _trip(output_size)
        # invert out = (in-1)*s - 2p + d*(k-1) + 1 per spatial dim
        filter_size = [
            (output_size[i] - (input.shape[2 + i] - 1) * stride[i]
             + 2 * padding[i] - 1) // dilation[i] + 1
            for i in range(3)
        ]
    else:
        filter_size = _trip(filter_size)
    filter_shape = [num_channels, num_filters // groups] + filter_size
    w = helper.create_parameter(attr=helper.param_attr, shape=filter_shape, dtype=dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [pre_bias]},
        attrs={
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
        },
    )
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int32"):
    """Sample a category per row of a probability matrix
    (nn.py sampling_id / sampling_id_op.cc)."""
    helper = LayerHelper("sampling_id")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "sampling_id",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"seed": seed},
    )
    return out


def data_norm(
    input,
    act=None,
    epsilon=1e-05,
    param_attr=None,
    data_layout="NCHW",
    in_place=False,
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    do_model_average_for_mean_and_var=False,
):
    """Batch-statistics normalization for CTR models (nn.py data_norm /
    data_norm_op.cc): accumulators are persistable state the op updates
    each step."""
    helper = LayerHelper("data_norm", **locals())
    dtype = helper.input_dtype()
    d = input.shape[-1]
    attr = param_attr or ParamAttr()
    from ..initializer import Constant

    bsz = helper.create_global_variable(
        name=unique_name.generate("data_norm_batch_size"),
        persistable=True, dtype=dtype, shape=[d],
    )
    bsum = helper.create_global_variable(
        name=unique_name.generate("data_norm_batch_sum"),
        persistable=True, dtype=dtype, shape=[d],
    )
    bsq = helper.create_global_variable(
        name=unique_name.generate("data_norm_batch_square_sum"),
        persistable=True, dtype=dtype, shape=[d],
    )
    helper.set_variable_initializer(bsz, Constant(1e4))
    helper.set_variable_initializer(bsum, Constant(0.0))
    helper.set_variable_initializer(bsq, Constant(1e4))
    out = helper.create_variable_for_type_inference(dtype)
    means = helper.create_variable_for_type_inference(dtype)
    scales = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "data_norm",
        inputs={"X": [input], "BatchSize": [bsz], "BatchSum": [bsum],
                "BatchSquareSum": [bsq]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales],
                 "BatchSizeOut": [bsz], "BatchSumOut": [bsum],
                 "BatchSquareSumOut": [bsq]},
        attrs={"epsilon": epsilon},
    )
    return helper.append_activation(out)
