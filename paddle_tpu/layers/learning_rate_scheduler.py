"""LR schedules (layers/learning_rate_scheduler.py analog).

The reference emits decay as in-graph ops over a global step counter; same
here — the counter is a persistable scalar incremented each step inside the
compiled program, so schedules compile into the training executable.
"""

import functools
import math

from .. import framework
from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import tensor, nn, ops


def _lrsched(fn):
    """Tag every op a schedule emits with the LRSched role
    (op_proto_maker.h OpRole::kLRSched analog) so the distribute
    transpiler can move the decay chain onto the pservers."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        prog = framework.default_main_program()
        with prog._op_role_guard("lrsched"):
            return fn(*args, **kwargs)

    return wrapper

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
    "append_LARS",
]


def _decay_step_counter(begin=0):
    helper = LayerHelper("global_step_counter")
    counter = helper.create_or_get_global_variable(
        name="@LR_DECAY_COUNTER@", dtype="float32", shape=[1], persistable=True
    )
    if not getattr(counter, "_initialized", False):
        helper.set_variable_initializer(counter, Constant(float(begin)))
        counter._initialized = True
        helper.append_op(
            "increment",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            attrs={"step": 1.0},
        )
        counter.stop_gradient = True
    return counter


@_lrsched
def noam_decay(d_model, warmup_steps):
    step = _decay_step_counter(1)
    a = step ** -0.5
    b = (warmup_steps ** -1.5) * step
    return (d_model ** -0.5) * nn.elementwise_min(a, b)


@_lrsched
def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate * (decay_rate ** div)


@_lrsched
def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate * ops.exp(-1 * decay_rate * div)


@_lrsched
def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate / (1 + decay_rate * div)


@_lrsched
def polynomial_decay(
    learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False
):
    step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(step / float(decay_steps))
        decay_steps_var = float(decay_steps) * div_res
        frac = step / decay_steps_var
    else:
        frac = nn.elementwise_min(
            step / float(decay_steps), tensor.fill_constant([1], "float32", 1.0)
        )
    return (learning_rate - end_learning_rate) * ((1 - frac) ** power) + end_learning_rate


def _step_lt(step, bound):
    """exact float32 mask: 1.0 while step < bound, else 0.0 (branch-free,
    compiles to a select — replaces per-step scalar control flow)."""
    from . import control_flow

    b = tensor.fill_constant([1], "float32", float(bound))
    return tensor.cast(control_flow.less_than(step, b), "float32")


@_lrsched
def piecewise_decay(boundaries, values):
    """lr = values[i] for step in [boundaries[i-1], boundaries[i]) —
    computed branch-free as a sum of exact interval masks."""
    assert len(boundaries) + 1 == len(values)
    step = _decay_step_counter()
    lr = tensor.fill_constant([1], "float32", 0.0)
    for i, v in enumerate(values):
        if i == 0:
            m = _step_lt(step, boundaries[0])
        elif i < len(boundaries):
            m = _step_lt(step, boundaries[i]) - _step_lt(step, boundaries[i - 1])
        else:
            m = 1.0 - _step_lt(step, boundaries[-1])
        lr = lr + m * v
    return lr


@_lrsched
def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = ops.floor(step / step_each_epoch)
    return 0.5 * learning_rate * (ops.cos(epoch * (math.pi / epochs)) + 1)


@_lrsched
def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    linear = start_lr + (end_lr - start_lr) * (step / float(warmup_steps))
    m = _step_lt(step, warmup_steps)
    if isinstance(learning_rate, float):
        learning_rate = tensor.fill_constant([1], "float32", learning_rate)
    return m * linear + (1.0 - m) * learning_rate


def append_LARS(params_grads, learning_rate, weight_decay):
    """Layer-wise adaptive rate scaling
    (learning_rate_scheduler.py:310): per parameter,

        lr_p = lr * |param| / (|grad| + weight_decay * |param|)

    written into `param.optimize_attr["learning_rate"]` as a Variable so
    the optimizer's per-param LR path picks it up.  Prefer
    fluid.optimizer.LarsMomentum (the fused momentum+LARS op) for
    training; this function is the reference-parity scheduler form."""
    out = []
    for param, grad in params_grads:
        if grad is None:
            continue
        prog = param.block.program
        with prog._optimized_guard([param, grad]):
            param_lr = (param.optimize_attr or {}).get("learning_rate", 1.0)
            param_norm = ops.sqrt(nn.reduce_sum(ops.square(param)))
            grad_norm = ops.sqrt(nn.reduce_sum(ops.square(grad)))
            base = (
                learning_rate
                if isinstance(param_lr, float) and param_lr == 1.0
                else learning_rate * param_lr
            )
            decayed_lr = base * param_norm / (
                grad_norm + weight_decay * param_norm)
            param.optimize_attr["learning_rate"] = decayed_lr
            out.append(decayed_lr)
    return out
