"""Tensor-building layers (python/paddle/fluid/layers/tensor.py analog)."""

import numpy as np

from .. import framework
from ..layer_helper import LayerHelper
from ..initializer import Constant

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "sums",
    "assign",
    "fill_constant",
    "fill_constant_batch_size_like",
    "ones",
    "zeros",
    "ones_like",
    "zeros_like",
    "argmax",
    "argmin",
    "argsort",
    "reverse",
    "linspace",
    "range",
    "diag",
    "eye",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype, persistable=persistable)


def create_parameter(
    shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None
):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", name=name)
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype, shape=shape, persistable=persistable, name=name
    )
    helper.set_variable_initializer(var, Constant(value))
    return var


def cast(x, dtype):
    if not isinstance(dtype, str):
        dtype = np.dtype(dtype).name
    helper = LayerHelper("cast")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": dtype},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(
        "concat", inputs={"X": input}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, framework.Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": [input]}, outputs={"Out": [output]})
    else:
        value = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(str(value.dtype))
        helper.append_op(
            "assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(value.shape),
                "values": value.flatten().tolist(),
                "np_dtype": str(value.dtype),
            },
        )
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": dtype, "value": float(value)},
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0
):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": dtype,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def ones(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype="float32", force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def ones_like(x, out=None):
    helper = LayerHelper("ones_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "fill_any_like", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"value": 1.0}
    )
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "arg_max", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "arg_min", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis},
    )
    return out, ids


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_variable_for_type_inference(x.dtype)
    if isinstance(axis, int):
        axis = [axis]
    helper.append_op(
        "reverse", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis}
    )
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "linspace",
        outputs={"Out": [out]},
        attrs={"start": float(start), "stop": float(stop), "num": int(num), "dtype": dtype},
    )
    return out


def range(start, end, step, dtype="int64"):
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "range",
        outputs={"Out": [out]},
        attrs={"start": start, "end": end, "step": step, "dtype": dtype},
    )
    return out


def diag(diagonal):
    helper = LayerHelper("diag")
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    helper.append_op("diag", inputs={"Diagonal": [diagonal]}, outputs={"Out": [out]})
    return out


def eye(num_rows, num_columns=None, dtype="float32"):
    helper = LayerHelper("eye")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "eye",
        outputs={"Out": [out]},
        attrs={
            "num_rows": num_rows,
            "num_columns": num_columns or num_rows,
            "dtype": dtype,
        },
    )
    return out


def _overflow_check(op_type):
    """isfinite_op.cc OverflowOp family: one [1]-bool reduction per op."""

    def layer(x):
        helper = LayerHelper(op_type)
        out = helper.create_variable_for_type_inference("bool")
        helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]})
        return out

    layer.__name__ = op_type
    return layer


has_inf = _overflow_check("has_inf")
has_nan = _overflow_check("has_nan")
isfinite = _overflow_check("isfinite")


def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    """Stack/concat a TensorArray's written prefix (tensor_array_to_tensor_op)."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "tensor_array_to_tensor", inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"axis": int(axis), "use_stack": bool(use_stack)},
    )
    return out


__all__ += ["has_inf", "has_nan", "isfinite", "tensor_array_to_tensor"]
