"""Control-flow layers (layers/control_flow.py analog).

The reference runs sub-blocks through nested interpreters (while_op.cc:36
with StepScopes).  TPU-natively, `While` builds a sub-block that the tracer
lowers into one `lax.while_loop` (compiled, no per-step dispatch), and
StaticRNN lowers to `lax.scan`.  Gradients of scan-backed RNN layers come
from vjp of the lowering; grad-of-while is not yet supported (use StaticRNN
or the padded rnn layers for trainable recurrences).
"""

import numpy as np

from .. import framework
from ..framework import Variable
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = [
    "While",
    "Switch",
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "cond",
    "IfElse",
    "StaticRNN",
    "DynamicRNN",
]


def _logical_op(op_type, x, y, out=None, cond=None):
    helper = LayerHelper(op_type)
    if out is None and cond is not None:
        out = cond
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={}
    )
    return out


def less_than(x, y, force_cpu=None, cond=None):
    return _logical_op("less_than", x, y, cond=cond)


def less_equal(x, y, cond=None):
    return _logical_op("less_equal", x, y, cond=cond)


def greater_than(x, y, cond=None):
    return _logical_op("greater_than", x, y, cond=cond)


def greater_equal(x, y, cond=None):
    return _logical_op("greater_equal", x, y, cond=cond)


def equal(x, y, cond=None):
    return _logical_op("equal", x, y, cond=cond)


def not_equal(x, y, cond=None):
    return _logical_op("not_equal", x, y, cond=cond)


def increment(x, value=1.0, in_place=True):
    from . import nn

    return nn.increment(x, value, in_place)


class While:
    """while_op analog lowering to lax.while_loop.

    Usage parity with control_flow.py:655:
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...
            layers.increment(i)
            layers.less_than(i, n, cond=cond)

    Loop-carried state = every outer var both read and written in the body.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond

    def block(self):
        return WhileGuard(self)


class WhileGuard:
    def __init__(self, while_op):
        self.while_op = while_op
        self.main_program = framework.default_main_program()

    def __enter__(self):
        self.block = self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub_block = self.main_program.current_block()
        self.main_program.rollback()
        parent = self.main_program.current_block()
        # loop-carried vars: outer vars written in the sub-block
        carried = []
        seen = set()
        for op in sub_block.ops:
            for name in op.output_arg_names():
                if name in seen:
                    continue
                if not sub_block.has_var_local(name) and parent._find_var_recursive(name):
                    seen.add(name)
                    carried.append(name)
        cond_name = self.while_op.cond_var.name
        parent.append_op(
            "while",
            inputs={"Condition": [cond_name]},
            outputs={"Out": list(carried)},
            attrs={"sub_block_idx": sub_block.idx, "carried_vars": list(carried)},
        )
        return True


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional two-branch conditional -> lax.cond.

    Both branches build sub-blocks; outputs must be shape/dtype-matched
    var lists.
    """
    main = framework.default_main_program()
    helper = LayerHelper("cond", name=name)

    def build(fn):
        blk = main.create_block()
        outs = fn()
        main.rollback()
        if outs is None:
            outs = []
        if isinstance(outs, Variable):
            outs = [outs]
        return blk, [o.name for o in outs]

    tblk, touts = build(true_fn)
    fblk, fouts = build(false_fn)
    if len(touts) != len(fouts):
        raise ValueError("cond branches must return same number of outputs")
    parent = main.current_block()
    out_vars = [
        parent.create_var(
            name=framework.unique_name.generate("cond_out"), dtype="float32", shape=None
        )
        for _ in touts
    ]
    parent.append_op(
        "cond",
        inputs={"Condition": [pred.name]},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={
            "sub_block_true_idx": tblk.idx,
            "sub_block_false_idx": fblk.idx,
            "true_outs": touts,
            "false_outs": fouts,
        },
    )
    if len(out_vars) == 1:
        return out_vars[0]
    return out_vars


class Switch:
    """Switch/case built on nested cond (control_flow.py:1286 parity)."""

    def __init__(self, name=None):
        raise NotImplementedError("Switch pending; use layers.cond")


class IfElse:
    def __init__(self, cond, name=None):
        raise NotImplementedError("IfElse pending; use layers.cond")


# ---------------------------------------------------------------------------
# tensor arrays (LOD_TENSOR_ARRAY analog, static-size on TPU)
# ---------------------------------------------------------------------------
def create_array(dtype):
    helper = LayerHelper("array")
    return helper.create_variable(
        name=framework.unique_name.generate("array"),
        dtype=dtype,
        shape=None,
        type=framework.VarType.LOD_TENSOR_ARRAY,
    )


def array_write(x, i, array=None):
    raise NotImplementedError(
        "tensor arrays pending — use StaticRNN/scan-based recurrences"
    )


def array_read(array, i):
    raise NotImplementedError(
        "tensor arrays pending — use StaticRNN/scan-based recurrences"
    )


def array_length(array):
    raise NotImplementedError("tensor arrays pending")


class StaticRNN:
    def __init__(self, name=None):
        raise NotImplementedError(
            "StaticRNN pending — use layers.dynamic_lstm/dynamic_gru (scan ops)"
        )


class DynamicRNN:
    def __init__(self, name=None):
        raise NotImplementedError(
            "DynamicRNN pending — use layers.dynamic_lstm/dynamic_gru (scan ops)"
        )
