"""Control-flow layers (layers/control_flow.py analog).

The reference runs sub-blocks through nested interpreters (while_op.cc:36
with StepScopes).  TPU-natively:

- ``While`` builds a sub-block the tracer lowers into one ``lax.while_loop``
  (forward-only); with ``max_iters`` it becomes a ``bounded_while`` op — a
  masked ``lax.scan`` that IS reverse-differentiable (SURVEY.md §7 hard
  part 3).
- ``StaticRNN`` (control_flow.py:429) and ``DynamicRNN`` (:1542) both emit a
  single ``recurrent`` op (recurrent_op.cc analog) whose lowering is one
  ``lax.scan`` over the step sub-block — gradients flow through the whole
  recurrence via the generic vjp machinery, replacing the reference's
  StepScopes + while_grad interpreter.
- Tensor arrays (:825 lod_tensor_to_array etc.) are static-capacity
  ``TensorArray`` pytrees (ops/control_ops.py).
- ``IfElse`` (:1412) is re-expressed as compute-both + row-wise select
  (static shapes; the reference's row splitting cannot compile on TPU).
- ``Switch`` (:1286) traces every case block and merges first-true-wins.
"""

import numpy as np

from .. import framework, unique_name
from ..framework import Variable
from ..layer_helper import LayerHelper
from . import tensor as tensor_layers

__all__ = [
    "While",
    "Switch",
    "increment",
    "array_write",
    "array_read",
    "array_length",
    "create_array",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "equal",
    "not_equal",
    "cond",
    "IfElse",
    "StaticRNN",
    "DynamicRNN",
    "lod_rank_table",
    "max_sequence_len",
    "lod_tensor_to_array",
    "array_to_lod_tensor",
    "shrink_memory",
]


def _logical_op(op_type, x, y, out=None, cond=None):
    helper = LayerHelper(op_type)
    if out is None and cond is not None:
        out = cond
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    helper.append_op(
        op_type, inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]}, attrs={}
    )
    return out


def less_than(x, y, force_cpu=None, cond=None):
    return _logical_op("less_than", x, y, cond=cond)


def less_equal(x, y, cond=None):
    return _logical_op("less_equal", x, y, cond=cond)


def greater_than(x, y, cond=None):
    return _logical_op("greater_than", x, y, cond=cond)


def greater_equal(x, y, cond=None):
    return _logical_op("greater_equal", x, y, cond=cond)


def equal(x, y, cond=None):
    return _logical_op("equal", x, y, cond=cond)


def not_equal(x, y, cond=None):
    return _logical_op("not_equal", x, y, cond=cond)


def increment(x, value=1.0, in_place=True):
    from . import nn

    return nn.increment(x, value, in_place)


def _sub_block_externals(program, blk, bound):
    """Outer-scope names a sub-block reads before writing — these become
    the op's Ext inputs so the generic vjp grad path sees them as
    differentiable leaves.  Shares the traversal with the tracer
    (core/trace.py) so build-time Ext lists and trace-time discovery can
    never disagree."""
    from ..core.trace import sub_block_external_reads

    return sub_block_external_reads(program, blk, bound)


class While:
    """while_op analog.

    Usage parity with control_flow.py:655:
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...
            layers.increment(i)
            layers.less_than(i, n, cond=cond)

    Loop-carried state = every outer var both read and written in the body.
    With ``max_iters`` set the loop lowers to a masked, reverse-
    differentiable ``lax.scan`` (bounded_while op) instead of
    ``lax.while_loop`` — required when gradients must flow through the loop.
    """

    def __init__(self, cond, is_test=False, name=None, max_iters=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.max_iters = max_iters

    def block(self):
        return WhileGuard(self)


class WhileGuard:
    def __init__(self, while_op):
        self.while_op = while_op
        self.main_program = framework.default_main_program()

    def __enter__(self):
        self.block = self.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        sub_block = self.main_program.current_block()
        self.main_program.rollback()
        parent = self.main_program.current_block()
        # loop-carried vars: outer vars written in the sub-block
        carried = []
        seen = set()
        for op in sub_block.ops:
            for name in op.output_arg_names():
                if name in seen:
                    continue
                if not sub_block.has_var_local(name) and parent._find_var_recursive(name):
                    seen.add(name)
                    carried.append(name)
        cond_name = self.while_op.cond_var.name
        if cond_name not in carried:
            raise RuntimeError(
                "While condition var '%s' is not updated in the loop body "
                "(infinite loop); recompute it with layers.less_than(..., "
                "cond=cond)" % cond_name
            )
        max_iters = self.while_op.max_iters
        if max_iters is not None:
            ext = [
                n
                for n in _sub_block_externals(
                    self.main_program, sub_block, carried
                )
                if parent._find_var_recursive(n) is not None
            ]
            parent.append_op(
                "bounded_while",
                inputs={"Carried": list(carried), "Ext": ext},
                outputs={"Out": list(carried)},
                attrs={
                    "sub_block_idx": sub_block.idx,
                    "carried_vars": list(carried),
                    "ext_names": ext,
                    "cond_name": cond_name,
                    "max_iters": int(max_iters),
                    "__bound_names__": list(carried) + ext,
                },
            )
        else:
            parent.append_op(
                "while",
                inputs={"Condition": [cond_name]},
                outputs={"Out": list(carried)},
                attrs={"sub_block_idx": sub_block.idx, "carried_vars": list(carried)},
            )
        return True


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Functional two-branch conditional -> lax.cond.

    Both branches build sub-blocks; outputs must be shape/dtype-matched
    var lists.
    """
    main = framework.default_main_program()
    helper = LayerHelper("cond", name=name)

    def build(fn):
        blk = main.create_block()
        outs = fn()
        main.rollback()
        if outs is None:
            outs = []
        if isinstance(outs, Variable):
            outs = [outs]
        return blk, [o.name for o in outs]

    tblk, touts = build(true_fn)
    fblk, fouts = build(false_fn)
    if len(touts) != len(fouts):
        raise ValueError("cond branches must return same number of outputs")
    parent = main.current_block()
    out_vars = [
        parent.create_var(
            name=unique_name.generate("cond_out"), dtype="float32", shape=None
        )
        for _ in touts
    ]
    parent.append_op(
        "cond",
        inputs={"Condition": [pred.name]},
        outputs={"Out": [v.name for v in out_vars]},
        attrs={
            "sub_block_true_idx": tblk.idx,
            "sub_block_false_idx": fblk.idx,
            "true_outs": touts,
            "false_outs": fouts,
        },
    )
    if len(out_vars) == 1:
        return out_vars[0]
    return out_vars


# ---------------------------------------------------------------------------
# Switch (control_flow.py:1286): first-true-wins case assignment
# ---------------------------------------------------------------------------
class Switch:
    """Piecewise assignment (the lr-schedule workhorse):

        with layers.Switch() as switch:
            with switch.case(cond1):
                tensor_layers.assign(v1, out)
            with switch.default():
                tensor_layers.assign(v2, out)

    Every case body becomes a sub-block; the emitted `switch` op traces all
    of them (pure under the functionalized scope) and merges the written
    vars with a first-true-wins jnp.where chain.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.main_program = framework.default_main_program()
        self.cases = []  # (cond_var_or_None, block, written_names)
        self.inside = False

    def case(self, condition):
        if not self.inside:
            raise ValueError("case() must be called inside `with Switch()`")
        if self.cases and self.cases[-1][0] is None:
            raise ValueError("default() must be the last branch of a Switch")
        return _SwitchCaseGuard(self, condition)

    def default(self):
        if not self.inside:
            raise ValueError("default() must be called inside `with Switch()`")
        return _SwitchCaseGuard(self, None)

    def __enter__(self):
        self.inside = True
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.inside = False
        self._complete()
        return True

    def _complete(self):
        if not self.cases:
            return
        parent = self.main_program.current_block()
        written = []
        for _, _, wnames in self.cases:
            for n in wnames:
                if n not in written:
                    written.append(n)
        # prior values of written vars (fallthrough when no case matches and
        # there is no default, and fill-in for cases that skip a var)
        cur = []
        for n in written:
            v = parent._find_var_recursive(n)
            if v is not None and (v.persistable or getattr(v, "op", None) is not None):
                cur.append(n)
        conds = [c for c, _, _ in self.cases if c is not None]
        case_blocks = [b.idx for c, b, _ in self.cases if c is not None]
        default_blocks = [b.idx for c, b, _ in self.cases if c is None]
        ext = []
        seen = set(written) | set(cur) | {c.name for c in conds}
        for _, blk, _ in self.cases:
            for n in _sub_block_externals(self.main_program, blk, cur):
                if n not in seen and parent._find_var_recursive(n) is not None:
                    seen.add(n)
                    ext.append(n)
        parent.append_op(
            "switch",
            inputs={"Cond": conds, "Cur": list(cur), "Ext": ext},
            outputs={"Out": list(written)},
            attrs={
                "written_names": list(written),
                "cur_names": list(cur),
                "ext_names": ext,
                "case_blocks": case_blocks,
                "default_block_idx": default_blocks[0] if default_blocks else -1,
                # keep sub-block attrs discoverable for analyze_block
                "sub_block_idxs": case_blocks + default_blocks,
                "__bound_names__": list(cur) + ext,
            },
        )


class _SwitchCaseGuard:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        self.block = self.switch.main_program.create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        blk = self.switch.main_program.current_block()
        self.switch.main_program.rollback()
        parent = self.switch.main_program.current_block()
        written = []
        for op in blk.ops:
            for name in op.output_arg_names():
                if (
                    name
                    and name not in written
                    and not blk.has_var_local(name)
                    and parent._find_var_recursive(name) is not None
                ):
                    written.append(name)
        self.switch.cases.append((self.condition, blk, written))
        return True


# ---------------------------------------------------------------------------
# IfElse (control_flow.py:1412): compute-both + row-select re-expression
# ---------------------------------------------------------------------------
class IfElse:
    """Row-conditional computation:

        ie = layers.IfElse(cond)          # cond: [batch, 1] bool
        with ie.true_block():
            ie.output(f(ie.input(x)))
        with ie.false_block():
            ie.output(g(ie.input(x)))
        out, = ie()

    The reference physically splits the batch by mask, runs each branch on
    its subset, and merges rows back.  Static XLA shapes can't do that, so
    both branches run on the FULL batch and the outputs merge with a
    row-wise select — same math, dense execution (the standard TPU trade).
    """

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        if not isinstance(cond, Variable):
            raise TypeError("IfElse cond must be a Variable")
        self.cond = cond
        self.helper = LayerHelper("ifelse", name=name)
        self._outs = {True: [], False: []}
        self._branch = None

    def input(self, x):
        if self._branch is None:
            raise ValueError("IfElse.input() must be called inside a branch block")
        return x

    def output(self, *outs):
        if self._branch is None:
            raise ValueError("IfElse.output() must be called inside a branch block")
        self._outs[self._branch].extend(outs)

    def true_block(self):
        return _IfElseBranch(self, True)

    def false_block(self):
        return _IfElseBranch(self, False)

    def __call__(self):
        t, f = self._outs[True], self._outs[False]
        if len(t) != len(f):
            raise ValueError(
                "IfElse branches produced %d vs %d outputs" % (len(t), len(f))
            )
        merged = []
        for tv, fv in zip(t, f):
            out = self.helper.create_variable_for_type_inference(tv.dtype)
            self.helper.append_op(
                "ifelse_select",
                inputs={"Cond": [self.cond], "X": [tv], "Y": [fv]},
                outputs={"Out": [out]},
            )
            merged.append(out)
        return merged


class _IfElseBranch:
    """Branch scope.  Ops are appended INLINE (compute-both lowering), so
    any op with effects beyond its dataflow outputs would fire regardless
    of the row condition — unlike the reference, which executes only the
    taken branch on its row subset (control_flow.py:1412).  The exit hook
    therefore REJECTS side-effecting ops (print, save, RPC sends) and
    persistable writes inside a branch with a clear error; pure RNG ops
    (dropout etc.) are fine — draws are per-row selected by the merge and
    advance no global state."""

    def __init__(self, ie, is_true):
        self.ie = ie
        self.is_true = is_true

    def __enter__(self):
        self.ie._branch = self.is_true
        block = self.ie.helper.main_program.current_block()
        self._block = block
        self._start = len(block.ops)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.ie._branch = None
        if exc_type is not None:
            return False
        from ..core.registry import OPS

        prog = self.ie.helper.main_program
        label = "true_block" if self.is_true else "false_block"

        def check_ops(ops, block):
            for op in ops:
                opdef = OPS.get(op.type)
                if opdef is not None and opdef.side_effect:
                    raise ValueError(
                        "IfElse %s contains side-effecting op '%s': "
                        "branches run under the compute-both lowering, so "
                        "its effect would fire for EVERY row regardless of "
                        "the condition — hoist it out of the branch (e.g. "
                        "Print the merged output instead)"
                        % (label, op.type)
                    )
                # an op whose persistable 'write' is a no-op in inference
                # mode (batch_norm's MeanOut/VarianceOut with is_test) is
                # fine; a genuinely mutating write is not
                if not bool(op.attrs.get("is_test", False)):
                    for name in op.output_arg_names():
                        v = block._find_var_recursive(name)
                        if v is not None and getattr(v, "persistable",
                                                     False):
                            raise ValueError(
                                "IfElse %s writes persistable var '%s': "
                                "the compute-both lowering would apply "
                                "the write unconditionally — return the "
                                "value via ie.output() and assign it "
                                "after the merge, or use layers.Switch "
                                "(whose case writes merge by condition)"
                                % (label, name)
                            )
                # recurse into sub-blocks (While bodies, Switch cases,
                # cond true/false blocks): their effects are just as
                # unconditional w.r.t. the IfElse row condition
                from ..core.trace import op_sub_blocks

                for bidx in op_sub_blocks(op):
                    sub = prog.blocks[bidx]
                    check_ops(sub.ops, sub)

        check_ops(self._block.ops[self._start:], self._block)
        return True


# ---------------------------------------------------------------------------
# tensor arrays (LOD_TENSOR_ARRAY analog, static capacity on TPU)
# ---------------------------------------------------------------------------
def create_array(dtype):
    helper = LayerHelper("array")
    return helper.create_variable(
        name=unique_name.generate("array"),
        dtype=dtype,
        shape=None,
        type=framework.VarType.LOD_TENSOR_ARRAY,
    )


def array_write(x, i, array=None, capacity=128):
    """write_to_array (tensor_array_read_write_op.cc).  The first write
    allocates a static `capacity`-slot store; arrays used as loop-carried
    state must be seeded with a write BEFORE the loop (so the carry has a
    concrete shape entering lax.while_loop)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    inputs = {"X": [x], "I": [i]}
    if getattr(array, "_array_written", False):
        inputs["Array"] = [array]
    helper.append_op(
        "write_to_array",
        inputs=inputs,
        outputs={"Out": [array]},
        attrs={"capacity": int(capacity)},
    )
    array._array_written = True
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype)
    helper.append_op(
        "read_from_array",
        inputs={"X": [array], "I": [i]},
        outputs={"Out": [out]},
    )
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "lod_array_length", inputs={"X": [array]}, outputs={"Out": [out]}
    )
    return out


def lod_rank_table(x, level=0, seq_len=None):
    """control_flow.py:741 — on TPU the rank table IS the per-sequence
    length vector (see ops/control_ops.py)."""
    helper = LayerHelper("lod_rank_table")
    out = helper.create_variable_for_type_inference("int32")
    inputs = {"X": [x]}
    if seq_len is not None:
        inputs["SeqLen"] = [seq_len]
    helper.append_op("lod_rank_table", inputs=inputs, outputs={"Out": [out]})
    return out


def max_sequence_len(rank_table):
    helper = LayerHelper("max_sequence_len")
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "max_sequence_len",
        inputs={"RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out


def lod_tensor_to_array(x, table=None):
    """control_flow.py:825: padded [B, T, ...] -> time-major TensorArray."""
    helper = LayerHelper("lod_tensor_to_array")
    arr = create_array(x.dtype)
    inputs = {"X": [x]}
    if table is not None:
        inputs["RankTable"] = [table]
    helper.append_op("lod_tensor_to_array", inputs=inputs, outputs={"Out": [arr]})
    arr._array_written = True
    return arr


def array_to_lod_tensor(x, table=None):
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(x.dtype)
    inputs = {"X": [x]}
    if table is not None:
        inputs["RankTable"] = [table]
    helper.append_op("array_to_lod_tensor", inputs=inputs, outputs={"Out": [out]})
    return out


def shrink_memory(x, i, table):
    """control_flow.py:1111 — zero-mask rows of sequences finished by step i
    (the static-shape analog of dropping them)."""
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "shrink_rnn_memory",
        inputs={"X": [x], "I": [i], "RankTable": [table]},
        outputs={"Out": [out]},
    )
    return out


# ---------------------------------------------------------------------------
# StaticRNN / DynamicRNN on the `recurrent` op (one lax.scan)
# ---------------------------------------------------------------------------
class _MemoryLink:
    def __init__(self, init, pre_mem):
        self.init = init
        self.pre_mem = pre_mem
        self.mem = None


class _RNNBlockGuard:
    def __init__(self, rnn):
        self.rnn = rnn
        self.main_program = rnn.helper.main_program

    def __enter__(self):
        self.rnn.status = StaticRNN.IN_RNN_BLOCK
        self.block = self.main_program.create_block()
        self.rnn._sub_block = self.block
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        self.main_program.rollback()
        self.rnn.status = StaticRNN.AFTER_RNN_BLOCK
        self.rnn._complete()
        return True


class StaticRNN:
    """StaticRNN (control_flow.py:429): fixed-length recurrence.

    Step inputs are TIME-MAJOR ([T, batch, ...]) exactly like the
    reference (`seq_len = x.shape[0]`); outputs come back [T, batch, ...].
    The whole step block lowers to one differentiable lax.scan via the
    `recurrent` op instead of the reference's recurrent_op StepScopes
    interpreter.
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.memories = {}  # pre_mem.name -> _MemoryLink
        self.inputs = []  # (outer var, in-block var)
        self.statics = []  # (outer var, in-block var)
        self.outputs = []  # outer stacked output vars
        self._inner_outputs = []
        self.seq_len = None
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self._sub_block = None
        self._time_major = True
        self._seq_len_var = None

    def step(self):
        return _RNNBlockGuard(self)

    def _assert_in_rnn_block_(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError("You must invoke {0} in rnn block".format(method))

    def _parent_block(self):
        prog = self.helper.main_program
        parent_idx = prog.current_block().parent_idx
        assert parent_idx >= 0
        return prog.block(parent_idx)

    def memory(
        self,
        init=None,
        shape=None,
        batch_ref=None,
        init_value=0.0,
        init_batch_dim_idx=0,
        ref_batch_dim_idx=1,
    ):
        self._assert_in_rnn_block_("memory")
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError(
                    "if init is None, memory at least need shape and batch_ref"
                )
            # the boot op runs in the parent block — if batch_ref is an
            # in-block step slice, reference the outer sequence instead
            # (whose batch dim is ref_batch_dim_idx=1 in time-major layout,
            # hence the reference's default)
            for outer, inner in self.inputs:
                if batch_ref.name == inner.name:
                    batch_ref = outer
                    break
            parent = self._parent_block()
            full_shape = list(shape)
            if len(full_shape) < 2:
                bdim = -1
                if batch_ref.shape and len(batch_ref.shape) > ref_batch_dim_idx:
                    bdim = batch_ref.shape[ref_batch_dim_idx] or -1
                full_shape.insert(init_batch_dim_idx, bdim)
            boot = parent.create_var(
                name=unique_name.generate(
                    "@".join([self.helper.name, "memory_boot"])
                ),
                shape=full_shape,
                dtype=batch_ref.dtype,
                persistable=False,
            )
            parent.append_op(
                "fill_constant_batch_size_like",
                inputs={"Input": [batch_ref]},
                outputs={"Out": [boot]},
                attrs={
                    "value": init_value,
                    "shape": [abs(d) if d != -1 else 1 for d in full_shape],
                    "dtype": boot.dtype,
                    "input_dim_idx": ref_batch_dim_idx,
                    "output_dim_idx": init_batch_dim_idx,
                },
            )
            return self.memory(init=boot)
        pre_mem = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "mem"])),
            dtype=init.dtype,
            shape=init.shape,
        )
        self.memories[pre_mem.name] = _MemoryLink(init=init, pre_mem=pre_mem)
        return pre_mem

    def step_input(self, x):
        self._assert_in_rnn_block_("step_input")
        if not isinstance(x, Variable):
            raise TypeError("step input takes a Variable")
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        elif x.shape[0] not in (None, -1) and self.seq_len != x.shape[0]:
            raise ValueError("Static RNN only take fix seq_len input")
        ipt = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype,
            shape=list(x.shape[1:]),
        )
        self.inputs.append((x, ipt))
        return ipt

    def static_input(self, x):
        self._assert_in_rnn_block_("static_input")
        s = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "static_in"])),
            dtype=x.dtype,
            shape=x.shape,
        )
        self.statics.append((x, s))
        return s

    def step_output(self, o):
        self._assert_in_rnn_block_("step_output")
        if not isinstance(o, Variable):
            raise TypeError("step output takes a Variable")
        self._inner_outputs.append(o)
        out = self._parent_block().create_var(
            name=unique_name.generate("@".join([self.helper.name, "out"])),
            dtype=o.dtype,
            shape=[self.seq_len] + list(o.shape or []),
        )
        self.outputs.append(out)

    def output(self, *outputs):
        for each in outputs:
            self.step_output(each)

    def update_memory(self, mem, var):
        if not isinstance(mem, Variable) or not isinstance(var, Variable):
            raise TypeError("update memory should take variables")
        self.memories[mem.name].mem = var

    def _complete(self):
        prog = self.helper.main_program
        parent = prog.current_block()
        sub = self._sub_block
        links = list(self.memories.values())
        for l in links:
            if l.mem is None:
                raise ValueError(
                    "memory %s never updated (call update_memory)" % l.pre_mem.name
                )
        x_names = [inner.name for _, inner in self.inputs]
        pre_names = [l.pre_mem.name for l in links]
        state_names = [l.mem.name for l in links]
        static_names = [inner.name for _, inner in self.statics]
        out_names = [o.name for o in self._inner_outputs]
        bound = x_names + pre_names + static_names
        ext = [
            n
            for n in _sub_block_externals(prog, sub, bound)
            if parent._find_var_recursive(n) is not None
        ]
        last_vars = [
            parent.create_var(
                name=unique_name.generate("@".join([self.helper.name, "last"])),
                dtype=l.init.dtype,
                shape=l.init.shape,
            )
            for l in links
        ]
        self.last_states = last_vars
        inputs = {
            "X": [outer for outer, _ in self.inputs],
            "InitState": [l.init for l in links],
            "Static": [outer for outer, _ in self.statics],
            "Ext": ext,
        }
        if self._seq_len_var is not None:
            inputs["SeqLen"] = [self._seq_len_var]
        parent.append_op(
            "recurrent",
            inputs=inputs,
            outputs={"Out": self.outputs, "LastState": last_vars},
            attrs={
                "sub_block_idx": sub.idx,
                "x_names": x_names,
                "pre_state_names": pre_names,
                "state_names": state_names,
                "out_names": out_names,
                "static_names": static_names,
                "ext_names": ext,
                "time_major": self._time_major,
                "is_reverse": False,
                "__bound_names__": bound,
            },
        )

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("RNN output can only be retrieved after rnn block")
        if len(self.outputs) == 0:
            raise ValueError("RNN has no output")
        if len(self.outputs) == 1:
            return self.outputs[0]
        return self.outputs


class DynamicRNN(StaticRNN):
    """DynamicRNN (control_flow.py:1542): variable-length recurrence.

    Padded re-expression of the reference's rank-table machinery: step
    inputs are BATCH-MAJOR padded tensors [batch, T, ...] plus an optional
    per-sequence length vector (`seq_len` on step_input); finished
    sequences hold their memory and emit zero outputs (the masking analog
    of shrink_rnn_memory + lod_tensor_to_array bucketing).
    """

    def __init__(self, name=None):
        super().__init__(name=name)
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self._time_major = False

    def block(self):
        return _RNNBlockGuard(self)

    def step_input(self, x, level=0, seq_len=None):
        self._assert_in_rnn_block_("step_input")
        if not isinstance(x, Variable):
            raise TypeError("step input takes a Variable")
        if seq_len is not None:
            self._seq_len_var = seq_len
        if self.seq_len is None:
            self.seq_len = x.shape[1] if len(x.shape) > 1 else None
        ipt = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "step_in"])),
            dtype=x.dtype,
            shape=[x.shape[0]] + list(x.shape[2:]),
        )
        self.inputs.append((x, ipt))
        return ipt

    def memory(
        self,
        init=None,
        shape=None,
        value=0.0,
        need_reorder=False,
        dtype="float32",
        **kwargs
    ):
        self._assert_in_rnn_block_("memory")
        if init is None:
            if shape is None:
                raise ValueError("memory needs init or shape")
            if not self.inputs:
                raise ValueError(
                    "call step_input before a shape-initialized memory "
                    "(it provides the batch reference)"
                )
            batch_ref = self.inputs[0][0]
            parent = self._parent_block()
            bdim = batch_ref.shape[0] if batch_ref.shape else -1
            full_shape = [bdim if bdim not in (None,) else -1] + list(shape)
            boot = parent.create_var(
                name=unique_name.generate(
                    "@".join([self.helper.name, "memory_boot"])
                ),
                shape=full_shape,
                dtype=dtype,
                persistable=False,
            )
            parent.append_op(
                "fill_constant_batch_size_like",
                inputs={"Input": [batch_ref]},
                outputs={"Out": [boot]},
                attrs={
                    "value": value,
                    "shape": [1] + [int(d) for d in shape],
                    "dtype": dtype,
                    "input_dim_idx": 0,
                    "output_dim_idx": 0,
                },
            )
            init = boot
        pre_mem = self.helper.create_variable(
            name=unique_name.generate("@".join([self.helper.name, "mem"])),
            dtype=init.dtype,
            shape=init.shape,
        )
        self.memories[pre_mem.name] = _MemoryLink(init=init, pre_mem=pre_mem)
        return pre_mem

    def step_output(self, o):
        self._assert_in_rnn_block_("step_output")
        self._inner_outputs.append(o)
        out = self._parent_block().create_var(
            name=unique_name.generate("@".join([self.helper.name, "out"])),
            dtype=o.dtype,
            shape=[o.shape[0] if o.shape else -1, self.seq_len]
            + list(o.shape[1:] if o.shape else []),
        )
        self.outputs.append(out)


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """print_op: passes input through and prints it at execution time
    (jax.debug.print on device)."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "print", inputs={"In": [input]}, outputs={"Out": [out]},
        attrs={"message": message or input.name},
    )
    return out


def is_empty(x, name=None):
    """is_empty_op: [1] bool, true when x has zero elements."""
    helper = LayerHelper("is_empty", name=name)
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("is_empty", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    """Permute batch rows into the rank table's order
    (reorder_lod_tensor_by_rank_op.cc)."""
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "reorder_lod_tensor_by_rank",
        inputs={"X": [x], "RankTable": [rank_table]},
        outputs={"Out": [out]},
    )
    return out


__all__ += ["Print", "is_empty", "reorder_lod_tensor_by_rank"]


def recompute(fn, *args):
    """Run ``fn(*args)`` in a rematerialization scope: every layer built
    inside contributes to ONE `recompute` op whose activations are
    recomputed during backward instead of stored (jax.checkpoint under the
    hood) — the standard memory-for-FLOPs trade for deep stacks:

        def block(x):
            h = layers.fc(x, 4*d, act="gelu")
            return layers.fc(h, d)
        y = layers.recompute(block, x)

    Returns fn's output variable(s), re-homed in the enclosing block."""
    from ..framework import default_main_program

    main = default_main_program()
    parent = main.current_block()
    sub = main.create_block()
    outs = fn(*args)
    main.rollback()
    out_list = [outs] if isinstance(outs, Variable) else list(outs)

    arg_names = [a.name for a in args if isinstance(a, Variable)]
    # parameters and other outer vars the scope reads are inputs too
    ext = _sub_block_externals(main, sub, set(arg_names))
    in_names = arg_names + ext

    # reject writes to OUTER variables that aren't returned: the scope's
    # env is private, so e.g. batch_norm moving-stat updates or assigns
    # into an outer var would be silently discarded (and the tracer would
    # then write back stale state) — fail loudly at build time instead
    out_name_set = {o.name for o in out_list}
    for op in sub.ops:
        for n in op.output_arg_names():
            if (
                n not in out_name_set
                and not sub.has_var_local(n)
                and parent._find_var_recursive(n) is not None
            ):
                raise ValueError(
                    "recompute scope writes outer variable '%s' (op '%s') "
                    "without returning it — stateful updates (batch_norm "
                    "moving stats, assigns into outer vars) cannot cross a "
                    "rematerialization boundary; return the value from fn "
                    "or move the stateful op outside the scope"
                    % (n, op.type)
                )
    parent_outs = []
    for o in out_list:
        v = parent.create_var(
            name=unique_name.generate(o.name + ".remat"),
            dtype=o.dtype,
            shape=o.shape,
        )
        parent_outs.append(v)
    # output shapes/dtypes copied from the sub-block vars above — the
    # abstract-eval infer_shape path can't run this op (it needs the
    # tracer's trace_block), and doesn't need to
    parent.append_op(
        "recompute",
        inputs={"X": list(in_names)},
        outputs={"Out": [v.name for v in parent_outs]},
        attrs={
            "sub_block_idx": sub.idx,
            "in_names": list(in_names),
            "out_names": [o.name for o in out_list],
            "__bound_names__": list(in_names),
        },
    )
    return parent_outs[0] if isinstance(outs, Variable) else tuple(parent_outs)


__all__ += ["recompute"]
