"""Input layers (python/paddle/fluid/layers/io.py analog): `data` declares a
feed slot; `py_reader` (io.py:635) gives a program its own input pipeline.

TPU re-expression of the reader-op stack (create_py_reader_op.cc,
create_double_buffer_reader_op.cc): the `read` op stays in the program as
the declaration of in-program inputs, but its outputs are satisfied by the
Executor from a native-blocking-queue-fed, device-prefetching pipeline
(reader/program_reader.py) — host IO cannot live inside the compiled XLA
step, so the executor boundary is where the queue is drained.
"""

from .. import framework, unique_name

__all__ = ["data", "py_reader", "read_file", "double_buffer"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, type=None, stop_gradient=True):
    """Declare an input variable (io.py:39 parity).

    `append_batch_size=True` prepends a -1 batch dim as in the reference.
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
    )
    return var


class PyReaderHandle:
    """What `py_reader` returns: a READER-typed var handle whose
    decoration/lifecycle methods proxy the runtime state
    (reader/program_reader.py)."""

    def __init__(self, var, state, out_vars):
        self._var = var
        self._state = state
        self._out_vars = out_vars

    @property
    def name(self):
        return self._var.name

    @property
    def out_names(self):
        return list(self._state.out_names)

    def decorate_paddle_reader(self, reader):
        self._state.decorate_paddle_reader(reader)

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_batch_generator(self, generator):
        self._state.decorate_batch_generator(generator)

    decorate_tensor_provider = decorate_batch_generator

    def start(self):
        self._state.start()

    def reset(self):
        self._state.reset()


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None, use_double_buffer=True):
    """In-program reader (io.py:635 parity): returns a reader handle;
    `read_file(reader)` yields the data vars.  Usage:

        reader = layers.py_reader(64, [[-1, 784], [-1, 1]], ['float32', 'int64'])
        img, label = layers.read_file(reader)
        ...
        reader.decorate_paddle_reader(paddle.batch(mnist.train(), 32))
        reader.start()
        while True:
            try:
                exe.run(fetch_list=[loss])     # no feed: the program reads
            except fluid.core.EOFException:
                reader.reset()
                break
    """
    from ..reader.program_reader import ProgramReader

    main = framework.default_main_program()
    block = main.current_block()
    rname = name or unique_name.generate("py_reader")
    reader_var = block.create_var(
        name=rname, shape=None, dtype="float32", type=framework.VarType.READER
    )
    out_vars = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        out_vars.append(
            block.create_var(
                name=unique_name.generate("%s_out%d" % (rname, i)),
                shape=list(shape),
                dtype=dtype,
                stop_gradient=True,
                is_data=True,
            )
        )
    state = ProgramReader(
        rname, [v.name for v in out_vars], shapes, dtypes, capacity
    )
    if not hasattr(main, "_py_readers"):
        main._py_readers = {}
    main._py_readers[rname] = state
    return PyReaderHandle(reader_var, state, out_vars)


def read_file(reader):
    """Emit the `read` op binding the reader's staged batches to its data
    vars (read_op.cc parity)."""
    block = framework.default_main_program().current_block()
    block.append_op(
        "read",
        inputs={"Reader": [reader.name]},
        outputs={"Out": [v.name for v in reader._out_vars]},
        attrs={"reader_name": reader.name},
    )
    outs = reader._out_vars
    return outs[0] if len(outs) == 1 else outs


def double_buffer(reader, place=None, name=None):
    """Compat pass-through: device double-buffering is built into the
    py_reader pipeline (stager thread prefetches to device)."""
    return reader
