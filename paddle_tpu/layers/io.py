"""Input layers (python/paddle/fluid/layers/io.py analog): `data` declares a
feed slot; py_reader/double-buffering live in paddle_tpu.reader (the TPU
input pipeline is host-side prefetch + device_put, not reader ops)."""

from .. import framework

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, type=None, stop_gradient=True):
    """Declare an input variable (io.py:39 parity).

    `append_batch_size=True` prepends a -1 batch dim as in the reference.
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
    )
    return var
