"""Input layers (python/paddle/fluid/layers/io.py analog): `data` declares a
feed slot; `py_reader` (io.py:635) gives a program its own input pipeline.

TPU re-expression of the reader-op stack (create_py_reader_op.cc,
create_double_buffer_reader_op.cc): the `read` op stays in the program as
the declaration of in-program inputs, but its outputs are satisfied by the
Executor from a native-blocking-queue-fed, device-prefetching pipeline
(reader/program_reader.py) — host IO cannot live inside the compiled XLA
step, so the executor boundary is where the queue is drained.
"""

from .. import framework, unique_name

__all__ = ["data", "py_reader", "read_file", "double_buffer"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, type=None, stop_gradient=True):
    """Declare an input variable (io.py:39 parity).

    `append_batch_size=True` prepends a -1 batch dim as in the reference.
    """
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    block = framework.default_main_program().current_block()
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        lod_level=lod_level,
        stop_gradient=stop_gradient,
        is_data=True,
    )
    return var


class PyReaderHandle:
    """What `py_reader` returns: a READER-typed var handle whose
    decoration/lifecycle methods proxy the runtime state
    (reader/program_reader.py)."""

    def __init__(self, var, state, out_vars):
        self._var = var
        self._state = state
        self._out_vars = out_vars

    @property
    def name(self):
        return self._var.name

    @property
    def out_names(self):
        return list(self._state.out_names)

    def decorate_paddle_reader(self, reader):
        self._state.decorate_paddle_reader(reader)

    decorate_sample_list_generator = decorate_paddle_reader

    def decorate_batch_generator(self, generator):
        self._state.decorate_batch_generator(generator)

    decorate_tensor_provider = decorate_batch_generator

    def start(self):
        self._state.start()

    def reset(self):
        self._state.reset()


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None, use_double_buffer=True):
    """In-program reader (io.py:635 parity): returns a reader handle;
    `read_file(reader)` yields the data vars.  Usage:

        reader = layers.py_reader(64, [[-1, 784], [-1, 1]], ['float32', 'int64'])
        img, label = layers.read_file(reader)
        ...
        reader.decorate_paddle_reader(paddle.batch(mnist.train(), 32))
        reader.start()
        while True:
            try:
                exe.run(fetch_list=[loss])     # no feed: the program reads
            except fluid.core.EOFException:
                reader.reset()
                break
    """
    from ..reader.program_reader import ProgramReader

    main = framework.default_main_program()
    block = main.current_block()
    rname = name or unique_name.generate("py_reader")
    reader_var = block.create_var(
        name=rname, shape=None, dtype="float32", type=framework.VarType.READER
    )
    out_vars = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        out_vars.append(
            block.create_var(
                name=unique_name.generate("%s_out%d" % (rname, i)),
                shape=list(shape),
                dtype=dtype,
                stop_gradient=True,
                is_data=True,
            )
        )
    state = ProgramReader(
        rname, [v.name for v in out_vars], shapes, dtypes, capacity
    )
    if not hasattr(main, "_py_readers"):
        main._py_readers = {}
    main._py_readers[rname] = state
    return PyReaderHandle(reader_var, state, out_vars)


def read_file(reader):
    """Emit the `read` op binding the reader's staged batches to its data
    vars (read_op.cc parity)."""
    block = framework.default_main_program().current_block()
    block.append_op(
        "read",
        inputs={"Reader": [reader.name]},
        outputs={"Out": [v.name for v in reader._out_vars]},
        attrs={"reader_name": reader.name},
    )
    outs = reader._out_vars
    return outs[0] if len(outs) == 1 else outs


def double_buffer(reader, place=None, name=None):
    """Compat pass-through: device double-buffering is built into the
    py_reader pipeline (stager thread prefetches to device)."""
    return reader


def batch(reader, batch_size):
    """layers/io.py batch: alias of the reader-decorator batcher (the
    in-program reader variant batches at the py_reader boundary)."""
    from ..reader.decorator import batch as _batch

    return _batch(reader, batch_size)


def shuffle(reader, buffer_size):
    """layers/io.py shuffle: alias of the reader-decorator shuffler."""
    from ..reader.decorator import shuffle as _shuffle

    return _shuffle(reader, buffer_size)


def load(out, file_path, load_as_fp16=False):
    """load_op: read a saved variable into `out` at execution time."""
    from ..layer_helper import LayerHelper

    helper = LayerHelper("load")
    helper.append_op(
        "load", inputs={}, outputs={"Out": [out]},
        attrs={"file_path": file_path},
    )
    return out


__all__ += ["batch", "shuffle", "load"]


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    """py_reader variant shaped by existing data vars (io.py
    create_py_reader_by_data): shapes/dtypes come from feed_list."""
    return py_reader(
        capacity,
        [list(v.shape) for v in feed_list],
        [v.dtype for v in feed_list],
        name=name,
        use_double_buffer=use_double_buffer,
    )


def random_data_generator(low, high, shapes, lod_levels=None, for_parallel=True):
    """random_data_generator_op analog: an in-program reader whose batches
    are uniform noise in [low, high) — the reference's synthetic-input
    benchmark path."""
    import numpy as np

    reader = py_reader(
        capacity=8,
        shapes=shapes,
        dtypes=["float32"] * len(shapes),
        name=unique_name.generate("random_data_reader"),
    )

    def gen():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(
                (rng.rand(*[abs(int(s)) for s in shape]) * (high - low) + low)
                .astype("float32")
                for shape in shapes
            )

    reader.decorate_batch_generator(gen)
    return reader


def open_files(filenames, shapes, dtypes, lod_levels=None, pass_num=1,
               thread_num=None, buffer_size=None, name=None):
    """open_files_op + recordio reader analog: an in-program reader fed by
    the native RecordIO scanner over `filenames` (each record a pickled
    tuple of arrays, as written by recordio_writer helpers)."""
    import pickle

    from .. import recordio as _recordio

    reader = py_reader(
        capacity=buffer_size or 64, shapes=shapes, dtypes=dtypes, name=name
    )

    def gen():
        for _ in range(pass_num):
            for fn in filenames:
                for rec in _recordio.Scanner(fn):
                    yield pickle.loads(rec)

    reader.decorate_batch_generator(gen)
    return reader


class Preprocessor:
    """layers/io.py Preprocessor analog: a host-side transform stage on a
    reader's batches (the reference builds a sub-block of ops; here the
    transform is a python callable applied in the feeder thread — same
    contract: reader in, transformed reader out).

        p = Preprocessor(reader)
        with p.block():
            p.set_transform(lambda img, lbl: ((img - 0.5) / 0.5, lbl))
    """

    def __init__(self, reader, name=None):
        self.reader = reader
        self._fn = None

    class _Block:
        def __init__(self, outer):
            self.outer = outer

        def __enter__(self):
            return self.outer

        def __exit__(self, *exc):
            return False

    def block(self):
        return Preprocessor._Block(self)

    def set_transform(self, fn):
        import numpy as np

        self._fn = fn

        def wrap_batch(gen):
            def wrapped():
                for batch in gen():
                    vals = (
                        tuple(batch.values())
                        if isinstance(batch, dict)
                        else batch if isinstance(batch, (tuple, list))
                        else (batch,)
                    )
                    out = fn(*vals)
                    yield out if isinstance(out, (tuple, list)) else (out,)

            return wrapped

        def wrap_rows(gen):
            # rows-style generators (decorate_paddle_reader) yield lists
            # of per-sample tuples: columnize so fn sees batched tensors
            # (the reference Preprocessor's contract), then emit
            # batch-style columns
            def wrapped():
                for rows in gen():
                    cols = tuple(
                        np.stack([np.asarray(r[i]) for r in rows])
                        for i in range(len(rows[0]))
                    )
                    out = fn(*cols)
                    yield out if isinstance(out, (tuple, list)) else (out,)

            return wrapped

        def rewrap(kind, gen):
            return ("batch", wrap_rows(gen) if kind == "rows" else wrap_batch(gen))

        # wrap the generator already installed on the reader's runtime
        # state (PyReaderHandle proxies to ProgramReader), and keep
        # wrapping anything installed later through EITHER decorator
        state = getattr(self.reader, "_state", self.reader)
        kind_gen = getattr(state, "_gen", None)
        if kind_gen is not None:
            state._gen = rewrap(*kind_gen)

        def set_batch(gen):
            state._gen = rewrap("batch", gen)

        def set_rows(gen):
            state._gen = rewrap("rows", gen)

        self.reader.decorate_batch_generator = set_batch
        self.reader.decorate_tensor_provider = set_batch
        self.reader.decorate_paddle_reader = set_rows
        self.reader.decorate_sample_list_generator = set_rows
        return self.reader


__all__ += ["create_py_reader_by_data", "random_data_generator",
            "open_files", "Preprocessor"]
