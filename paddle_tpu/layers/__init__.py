"""fluid.layers equivalent: op-emitting layer functions."""

from . import nn, tensor, ops, io, control_flow, metric_op, math_op_patch, detection
from . import sequence, learning_rate_scheduler, nn_extras
from . import layer_function_generator
from .nn import *  # noqa: F401,F403
from .nn_extras import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .ops import *  # noqa: F401,F403
from .io import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .metric_op import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .layer_function_generator import *  # noqa: F401,F403
