"""Host-side metric accumulators (python/paddle/fluid/metrics.py analog):
update with per-batch numpy fetches, eval() aggregates across batches."""

import numpy as np

__all__ = [
    "MetricBase",
    "CompositeMetric",
    "Precision",
    "Recall",
    "Accuracy",
    "ChunkEvaluator",
    "EditDistance",
    "DetectionMAP",
    "Auc",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def reset(self):
        for attr, value in self.__dict__.items():
            if not attr.startswith("_"):
                if isinstance(value, (int, float)):
                    setattr(self, attr, 0)
                elif isinstance(value, (np.ndarray,)):
                    setattr(self, attr, np.zeros_like(value))

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    """Binary precision: preds are probabilities, labels {0,1}."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        rc = self.tp + self.fn
        return float(self.tp) / rc if rc != 0 else 0.0


class Accuracy(MetricBase):
    """Weighted running accuracy (feed per-batch acc + batch weight)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """F1 over chunk counts (feed num_infer/num_label/num_correct)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.instance_error += int((distances > 0).sum())
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data")
        return (
            self.total_distance / self.seq_num,
            float(self.instance_error) / self.seq_num,
        )


class Auc(MetricBase):
    """ROC AUC via threshold histogram (metrics.py Auc parity)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] > 1 else preds.reshape(-1)
        bins = np.minimum(
            (pos_prob * self._num_thresholds).astype(int), self._num_thresholds
        )
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = tot_neg = auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            new_pos = tot_pos + self._stat_pos[idx]
            new_neg = tot_neg + self._stat_neg[idx]
            auc += self.trapezoid_area(tot_neg, new_neg, tot_pos, new_pos)
            tot_pos, tot_neg = new_pos, new_neg
            idx -= 1
        return auc / (tot_pos * tot_neg) if tot_pos > 0 and tot_neg > 0 else 0.0


class DetectionMAP(MetricBase):
    """Mean average precision for detection (metrics.py DetectionMAP /
    detection/detection_map_op.cc re-expressed as a host-side accumulator,
    matching the other MetricBase evaluators).

    update() takes per-image padded arrays:
      detections: [K, 6] rows (label, score, x1, y1, x2, y2); label<0 = pad
      gt_boxes:   [G, 4]; gt_labels: [G]; rows past gt_count are padding
    eval() returns mAP over 11-point interpolated precision ("11point") or
    the integral AP ("integral").
    """

    def __init__(self, name=None, overlap_threshold=0.5, ap_version="integral"):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self.reset()

    def reset(self, executor=None, reset_program=None):
        self._dets = {}  # class -> list of (score, tp)
        self._npos = {}  # class -> #gt boxes

    @staticmethod
    def _iou(a, b):
        ax1, ay1, ax2, ay2 = a
        bx1, by1, bx2, by2 = b
        iw = min(ax2, bx2) - max(ax1, bx1)
        ih = min(ay2, by2) - max(ay1, by1)
        if iw <= 0 or ih <= 0:
            return 0.0
        inter = iw * ih
        ua = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gt_boxes, gt_labels, gt_count=None,
               difficult=None):
        """difficult: optional [G] 0/1 flags — VOC convention: difficult
        ground truths are excluded from the positive count and a
        detection matched to one is neither TP nor FP.  Ground-truth
        rows with label < 0 are padding and skipped."""
        detections = np.asarray(detections)
        gt_boxes = np.asarray(gt_boxes)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        n_gt = int(gt_count) if gt_count is not None else gt_boxes.shape[0]
        diff = (np.asarray(difficult).reshape(-1).astype(bool)
                if difficult is not None else np.zeros(n_gt, bool))
        for g in range(n_gt):
            c = int(gt_labels[g])
            if c < 0 or diff[g]:
                continue
            self._npos[c] = self._npos.get(c, 0) + 1
        used = np.zeros(n_gt, bool)
        dets = detections[detections[:, 0] >= 0]
        order = np.argsort(-dets[:, 1])
        for d in dets[order]:
            c = int(d[0])
            best, best_g = 0.0, -1
            for g in range(n_gt):
                if int(gt_labels[g]) != c or used[g]:
                    continue
                ov = self._iou(d[2:6], gt_boxes[g])
                if ov > best:
                    best, best_g = ov, g
            tp = best >= self.overlap_threshold and best_g >= 0
            if tp:
                if diff[best_g]:
                    # matched a difficult gt: ignore this detection
                    continue
                used[best_g] = True
            self._dets.setdefault(c, []).append((float(d[1]), bool(tp)))

    def eval(self, executor=None, eval_program=None):
        aps = []
        for c, npos in self._npos.items():
            recs = sorted(self._dets.get(c, []), key=lambda t: -t[0])
            tps = np.cumsum([1.0 if tp else 0.0 for _, tp in recs])
            fps = np.cumsum([0.0 if tp else 1.0 for _, tp in recs])
            if len(recs) == 0 or npos == 0:
                aps.append(0.0)
                continue
            rec = tps / npos
            prec = tps / np.maximum(tps + fps, 1e-12)
            if self.ap_version == "11point":
                ap = 0.0
                for t in np.arange(0.0, 1.01, 0.1):
                    p = prec[rec >= t].max() if np.any(rec >= t) else 0.0
                    ap += p / 11.0
            else:  # integral
                ap, prev_r = 0.0, 0.0
                for r, p in zip(rec, prec):
                    ap += (r - prev_r) * p
                    prev_r = r
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
