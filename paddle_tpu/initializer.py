"""Parameter initializers (python/paddle/fluid/initializer.py analog).

Each initializer appends an init op to the *startup program*; running the
startup program once materializes parameters in the scope — same two-program
contract as the reference.  Random inits lower to jax.random draws.
"""

import contextlib
import math

import numpy as np

from . import framework

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "Bilinear",
    "NumpyArrayInitializer",
    "force_init_on_cpu",
    "init_on_cpu",
]


def force_init_on_cpu():
    return False


@contextlib.contextmanager
def init_on_cpu():
    """initializer.py init_on_cpu parity: the reference forces wrapped
    initializers (lr-scheduler counters) onto CPU via force_cpu attrs.
    Under XLA the executor owns placement — host-side scalars stay host
    scalars until fed — so this is an accepted no-op context for
    migrating code."""
    yield


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            "fill_constant",
            outputs={"Out": [var]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            "uniform_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "gaussian_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 2:
        rf = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    else:
        fan_in = fan_out = int(np.prod(shape))
    return fan_in, fan_out


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init (used by conv2d_transpose upsampling)."""

    def __call__(self, var, block):
        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = int(np.prod(shape))
        flat = np.zeros(size, dtype="float32")
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        weight = flat.reshape(shape)
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            "assign_value",
            outputs={"Out": [var]},
            attrs={
                "shape": list(self.value.shape),
                "values": self.value.flatten().tolist(),
                "np_dtype": str(self.value.dtype),
            },
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
