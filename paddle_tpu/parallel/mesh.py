"""Device-mesh helpers (the NCCLContextMap analog — nccl_helper.h:82 —
except the 'communicators' are implicit in XLA collectives over the mesh)."""

import numpy as np
import jax
from jax.sharding import Mesh

import functools as _functools

try:  # jax >= 0.5 promoted shard_map to the top level
    from jax import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        # the promoted API renamed check_rep -> check_vma; translate so
        # callers written against either name work on both branches
        if "check_rep" in kwargs:
            kwargs["check_vma"] = kwargs.pop("check_rep")
        return _shard_map(*args, **kwargs)
except ImportError:  # pre-promotion home (this sandbox's jax 0.4.x)
    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        # the old replication checker predates vma tracking: it has no
        # rule for pallas_call and rejects cond branches the new checker
        # accepts, so bodies written against the promoted API need it off
        kwargs.setdefault("check_rep", False)
        kwargs.pop("check_vma", None)
        return _shard_map(*args, **kwargs)

__all__ = ["make_mesh", "default_mesh", "mesh_axis_sizes", "dp_mesh",
           "shard_map", "vma_of", "pcast_varying"]


def vma_of(*xs):
    """Union of the inputs' varying-mesh-axes.  ``jax.typeof``/vma
    tracking is a newer-jax API; on builds without it (this sandbox's
    0.4.x) nothing is tracked and the set is empty."""
    typeof = getattr(jax, "typeof", None)
    out = frozenset()
    if typeof is None:
        return out
    for x in xs:
        out = out | getattr(typeof(x), "vma", frozenset())
    return out


def pcast_varying(v, axes):
    """``jax.lax.pcast(v, axes, to="varying")`` where available; identity
    on jax builds without vma tracking (old shard_map's check_rep model
    needs no explicit cast for a value to be device-varying)."""
    pcast = getattr(jax.lax, "pcast", None)
    return pcast(v, axes, to="varying") if pcast is not None else v


def make_mesh(axes, devices=None):
    """axes: dict name->size in order, e.g. {"dp": 2, "mp": 4}. Use -1 for
    one axis to absorb the remaining devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError("mesh %s needs %d devices, have %d" % (axes, total, n))
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh(axis_name="dp"):
    return make_mesh({axis_name: -1})


def mesh_axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def dp_mesh(nranks, axis_name="dp"):
    """Data-parallel mesh for the collective dist backend: exactly
    `nranks` devices on one axis, spanning processes when jax.distributed
    is initialized (one device per trainer process) or local virtual
    devices for single-process CPU CI.  Fails loudly on a device deficit
    — a silent smaller mesh would hang the psum rendezvous."""
    devices = jax.devices()
    if len(devices) < nranks:
        raise ValueError(
            "collective mode needs %d devices for the %r mesh, but jax "
            "sees %d — launch %d processes (init_collective) or set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=%d for a "
            "single-process CPU mesh"
            % (nranks, axis_name, len(devices), nranks, nranks))
    return make_mesh({axis_name: nranks}, devices=devices[:nranks])
