"""Device-mesh helpers (the NCCLContextMap analog — nccl_helper.h:82 —
except the 'communicators' are implicit in XLA collectives over the mesh)."""

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "mesh_axis_sizes"]


def make_mesh(axes, devices=None):
    """axes: dict name->size in order, e.g. {"dp": 2, "mp": 4}. Use -1 for
    one axis to absorb the remaining devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError("mesh %s needs %d devices, have %d" % (axes, total, n))
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh(axis_name="dp"):
    return make_mesh({axis_name: -1})


def mesh_axis_sizes(mesh):
    return dict(zip(mesh.axis_names, mesh.devices.shape))
