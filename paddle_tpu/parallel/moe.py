"""Expert parallelism (ep axis): Switch (top-1) / GShard (top-2) MoE with
dense capacity-bucketed dispatch and all-to-all expert exchange.

The reference has no MoE; this completes the parallelism set (dp/mp/pp/
sp/ep) the TPU-native way: gating and dispatch are dense one-hot einsums
(no data-dependent shapes — everything tiles onto the MXU), experts are
sharded over the `ep` mesh axis, and tokens travel to their expert's
device and back via `jax.lax.all_to_all` over ICI inside one `shard_map`.
Differentiable end to end (`jax.grad` through the all_to_alls gives the
backward exchange for free).

Pattern per the public Switch-Transformer/GShard formulation: each device
routes its local tokens into per-expert capacity buckets [E, C, D], the
all-to-all regroups to [E_local, S*C, D] so every device runs only its
experts, and the reverse all-to-all + combine einsum scatter the results
back to token order.  Tokens over capacity are dropped (standard; raise
capacity_factor to trade memory for coverage) and the DROPPED FRACTION is
returned as a metric so silent drops are observable.  top_k=2 gives
GShard gating: second-choice routing with gates renormalized over the
chosen pair and capacity positions assigned first-choice-first.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


from .pipeline import stack_stage_params as _stack_params

# same leading-dim stacking as pipeline stages, one shared body
stack_expert_params = _stack_params


def _dispatch_tensors(xl, gate_w, n_experts, capacity, top_k=1):
    """Top-k routing of local tokens: returns (dispatch [B,E,C] one-hot,
    combine [B,E,C] prob-weighted, aux load-balance loss, dropped
    fraction of routing decisions).

    Routing bookkeeping (one-hots, cumsum positions) runs in float32
    regardless of the activation dtype: a bf16 cumsum goes inexact past
    256 tokens-per-expert and would silently double-book bucket slots."""
    logits = (xl @ gate_w).astype(jnp.float32)  # [B, E]
    probs = jax.nn.softmax(logits, axis=-1)

    onehots, gates = [], []
    masked = probs
    for _ in range(top_k):
        expert = jnp.argmax(masked, axis=-1)  # [B]
        oh = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)
        gates.append(jnp.sum(probs * oh, axis=-1))
        onehots.append(oh)
        masked = masked * (1.0 - oh)
    if top_k > 1:  # GShard: renormalize gates over the selected experts
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    dispatch = jnp.zeros((xl.shape[0], n_experts, capacity), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    counts = jnp.zeros((n_experts,), jnp.float32)
    routed = kept = 0.0
    for oh, gate in zip(onehots, gates):
        # bucket positions: later choices queue behind every earlier
        # choice's assignments for that expert (GShard priority order)
        pos = jnp.cumsum(oh, axis=0) * oh - oh + counts[None, :] * oh
        in_cap = (pos < capacity).astype(jnp.float32) * oh
        pos_oh = jax.nn.one_hot(
            jnp.sum(pos * oh, axis=-1).astype(jnp.int32), capacity,
            dtype=jnp.float32,
        )  # [B, C]
        d = in_cap[:, :, None] * pos_oh[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        counts = counts + jnp.sum(oh, axis=0)
        routed = routed + jnp.sum(oh)
        kept = kept + jnp.sum(in_cap)
    dropped = 1.0 - kept / jnp.maximum(routed, 1.0)

    # Switch aux loss on first-choice routing:
    # E * sum_e fraction_routed_e * mean_prob_e
    frac = jnp.mean(onehots[0], axis=0)
    mean_p = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(frac * mean_p)
    return dispatch.astype(xl.dtype), combine.astype(xl.dtype), aux, dropped


def switch_moe(expert_fn, mesh, axis="ep", capacity_factor=1.0, top_k=1):
    """Build an expert-parallel MoE apply:
    fn(gate_w, stacked_expert_params, x) -> (y, aux_loss, dropped_frac).

    expert_fn(params, h) -> h' applies ONE expert to a [N, D] token block.
    gate_w: [D, E] router weights (replicated).  stacked_expert_params:
    leaves [E, ...] (see stack_expert_params), sharded over `axis` so each
    device holds E/S experts.  x: [B, D] global tokens, sharded over
    `axis` on the batch dim (data-parallel across the expert group).
    top_k=1 is Switch routing; top_k=2 is GShard.  dropped_frac is the
    mesh-mean fraction of routing decisions that overflowed capacity —
    fetch it alongside aux_loss to see silent drops.
    """
    S = mesh.shape[axis]

    def _apply(gate_w, stacked_params, x):
        E = gate_w.shape[-1]
        assert E % S == 0, "experts %d must divide ep axis %d" % (E, S)
        B = x.shape[0]
        assert B % S == 0, "tokens %d must divide ep axis %d" % (B, S)
        Bl = B // S
        capacity = max(1, int(capacity_factor * top_k * Bl / E + 0.9999))

        def per_device(gate_w, params_local, xl):
            dispatch, combine, aux, dropped = _dispatch_tensors(
                xl, gate_w, E, capacity, top_k)
            # bucket local tokens per expert: [E, C, D]
            expert_in = jnp.einsum("bec,bd->ecd", dispatch, xl)
            # all-to-all: every device keeps only its experts' buckets and
            # receives those buckets from every peer -> [E/S, S*C, D]
            expert_in = jax.lax.all_to_all(
                expert_in, axis, split_axis=0, concat_axis=1, tiled=True
            )
            out = jax.vmap(expert_fn)(params_local, expert_in)
            # reverse exchange back to [E, C, D] in source-token order
            out = jax.lax.all_to_all(
                out, axis, split_axis=1, concat_axis=0, tiled=True
            )
            yl = jnp.einsum("bec,ecd->bd", combine, out)
            aux = jax.lax.pmean(aux, axis)
            dropped = jax.lax.pmean(dropped, axis)
            return yl, aux, dropped

        from .mesh import shard_map

        spec_params = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        y, aux, dropped = shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), spec_params, P(axis)),
            out_specs=(P(axis), P(), P()),
        )(gate_w, stacked_params, x)
        return y, aux, dropped

    return _apply


def moe_reference(expert_fn, gate_w, params_list, x, capacity, top_k=1):
    """Single-device reference with identical routing/capacity semantics
    (for parity tests): same dense dispatch, no collectives."""
    E = gate_w.shape[-1]
    dispatch, combine, aux, dropped = _dispatch_tensors(
        x, gate_w, E, capacity, top_k)
    expert_in = jnp.einsum("bec,bd->ecd", dispatch, x)
    outs = jnp.stack(
        [expert_fn(p, expert_in[e]) for e, p in enumerate(params_list)], 0
    )
    return jnp.einsum("bec,ecd->bd", combine, outs), aux, dropped
