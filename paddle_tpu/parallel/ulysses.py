"""All-to-all (Ulysses-style) sequence parallelism — the second
long-context strategy next to ring attention (parallel/ring.py).

Where ring attention keeps Q resident and rotates K/V blocks around the
`sp` axis (T/n memory, n ppermute hops), the all-to-all form re-shards
once: tokens arrive sharded on the TIME axis, one all_to_all turns that
into a HEAD-sharded layout so every device runs ordinary full-sequence
attention for H/n heads, and a second all_to_all restores time sharding.
Two collectives total regardless of sequence length — the better trade
when heads divide the axis and the per-device full-T score matrix fits
(flash attention inside keeps it O(T) anyway).

Pattern per the public DeepSpeed-Ulysses formulation, expressed as XLA
collectives under one shard_map.  Differentiable end to end (all_to_all
transposes to the reverse all_to_all).
"""

import functools

import jax
from jax.sharding import PartitionSpec as P


def _attention(q, k, v, causal, scale, window=0):
    """Full-sequence attention on local heads [B, h, T, D] — flash kernel
    under FLAGS_use_pallas via the shared fused-attention dispatch
    (window: sliding-window masking, since every head sees the FULL
    sequence here the op's banded mask applies globally)."""
    from ..ops import nn_ops  # noqa: F401  (registers fused_attention)
    from ..core.registry import get_op

    class _Ctx:
        rng_key = None

        def rng(self, attrs):  # pragma: no cover - attention needs no rng
            raise RuntimeError("no rng in fused attention")

    out = get_op("fused_attention").lower(
        _Ctx(), {"Q": [q], "K": [k], "V": [v]},
        {"causal": causal, "scale": scale, "window": int(window)},
    )
    return out["Out"][0]


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      window=0):
    """Per-device body (call under shard_map): q/k/v [B, H, T_local, D]
    sharded on time -> output [B, H, T_local, D] sharded on time.

    all_to_all #1: scatter heads / gather time -> [B, H/n, T, D]
    local attention over full T on H/n heads
    all_to_all #2: scatter time / gather heads -> back.
    """
    n = jax.lax.psum(1, axis_name)
    B, H, Tl, D = q.shape
    assert H % n == 0, (
        "ulysses needs heads %d divisible by %s=%d" % (H, axis_name, n)
    )
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    def scatter_heads(x):  # [B, H, Tl, D] -> [B, H/n, n*Tl, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def scatter_time(x):  # [B, H/n, n*Tl, D] -> [B, H, Tl, D]
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = _attention(qh, kh, vh, causal, scale, window)
    return scatter_time(out)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                              window=0):
    """Convenience wrapper mirroring ring_attention_sharded: q/k/v
    [B, H, T, D] global, sharded over `axis_name` on the time dim."""
    from .mesh import shard_map

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def inner(ql, kl, vl):
        return ulysses_attention(ql, kl, vl, axis_name, causal=causal,
                                 window=window)

    return inner(q, k, v)
