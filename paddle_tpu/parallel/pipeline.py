"""Pipeline parallelism (pp axis) over a mesh axis, two schedules:

- `gpipe`: classic fill-drain over M microbatches and S stages (M+S-1
  ticks); `jax.grad` differentiates straight through the scanned
  ppermute hops (the transpose is the reverse ring), at the cost of
  stashing O(M) activations per stage.
- `one_f_one_b`: hand-scheduled 1F1B train step — each microbatch's
  backward runs as soon as its forward clears the pipe, holding only a
  2S-1 circular buffer of stage inputs (O(S) activation memory,
  independent of M).

The reference framework has no pipeline engine (its multi-device story is
data-parallel only — SURVEY §2.9); this is the TPU-native extension that
completes the dp/mp/pp/sp/ep parallelism set.  Every stage lives on one
slice of the `pp` mesh axis, activations hop stage to stage over ICI
with `ppermute`, and each schedule is a `lax.scan` inside one
`shard_map` — XLA sees a single static program.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = [
    "stack_stage_params",
    "gpipe",
    "one_f_one_b",
    "pipeline_mlp_stages",
    "pipeline_transformer_stages",
    "sequential_reference",
]


def stack_stage_params(params_list):
    """[per-stage pytree, ...] -> one pytree with a leading stage dim,
    ready to shard along the pp axis (each device holds its stage's
    slice)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *params_list
    )


def gpipe(stage_fn, mesh, axis="pp", n_microbatches=None,
          param_specs=None, batch_axis=None):
    """Build a pipelined apply: fn(stacked_params, x) -> y.

    stage_fn(params, x_mb) -> y_mb computes ONE stage on ONE microbatch;
    all stages must map equal shapes (x_mb and y_mb shapes match across
    stages).  stacked_params: pytree with leading stage dim S == mesh
    size along `axis` (see stack_stage_params).  x: [B, ...] global
    batch; B must divide into n_microbatches (default: S).

    Composes with the other mesh axes for 3-axis dp x pp x tp:

    - `param_specs`: optional pytree of PartitionSpecs for the stacked
      params (leading dim MUST be `axis`); shard the tensor dims over a
      tp axis and have stage_fn reduce with ``jax.lax.psum(.., tp)``
      (megatron column/row-parallel inside each pipeline stage).
    - `batch_axis`: optional mesh axis sharding the batch dim of x/y —
      each dp slice runs its own fill-drain pipeline.

    Returns the full [B, ...] output replicated along `axis` (the last
    stage's result is broadcast back with a psum, one small collective).
    """
    S = mesh.shape[axis]

    def _pipelined(stacked_params, x):
        M = n_microbatches or S
        B = x.shape[0]
        assert B % M == 0, "batch %d must divide microbatches %d" % (B, M)
        mb = B // M
        xm = x.reshape((M, mb) + x.shape[1:])

        def per_device(params, xm_local):
            # params leaves arrive as [1, ...] (this device's stage slice)
            params = jax.tree_util.tree_map(lambda p: p[0], params)
            idx = jax.lax.axis_index(axis)
            ticks = M + S - 1
            zero = jnp.zeros_like(xm_local[0])
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]

            def tick(carry, t):
                recv = carry
                # stage 0 injects microbatch t during the fill phase;
                # later stages consume what arrived from the left
                inject = xm_local[jnp.minimum(t, M - 1)]
                use_inject = jnp.logical_and(idx == 0, t < M)
                inp = jnp.where(use_inject, inject, recv)
                out = stage_fn(params, inp)
                nxt = jax.lax.ppermute(out, axis, fwd_perm)
                # last stage emits microbatch t-(S-1) at tick t
                emit = jnp.where(
                    jnp.logical_and(idx == S - 1, t >= S - 1), out, zero
                )
                return nxt, emit

            # the scan carry crosses ppermute, so its type is
            # device-varying over `axis`; the stable shard_map tracks this
            # in types — cast the replicated init to varying to match
            from .mesh import pcast_varying

            init = pcast_varying(zero, axis)
            _, emitted = jax.lax.scan(tick, init, jnp.arange(ticks))
            # emitted: [ticks, mb, ...]; microbatch m sits at tick m+S-1
            ym = emitted[S - 1 :]
            # broadcast the last stage's result to every pp slice so the
            # caller sees a replicated [B, ...] output
            ym = jax.lax.psum(
                jnp.where(idx == S - 1, ym, jnp.zeros_like(ym)), axis
            )
            # keep [M, mb_local, ...]: flattening per-shard would permute
            # the global batch order once batch_axis concatenation applies
            return ym

        from .mesh import shard_map

        if param_specs is not None:
            for spec in jax.tree_util.tree_leaves(
                    param_specs, is_leaf=lambda s: isinstance(s, P)):
                if not (len(spec) >= 1 and spec[0] == axis):
                    # without the leading stage-dim shard, per_device's
                    # p[0] silently computes every stage with stage-0
                    # weights — fail loudly instead
                    raise ValueError(
                        "gpipe param_specs: every leaf spec must shard "
                        "its leading (stage) dim over %r, got %s"
                        % (axis, spec))
        spec_params = (
            param_specs if param_specs is not None
            else jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        )
        # microbatches are reshaped to [M, mb, ...]: the batch axis (if
        # any) shards the per-microbatch dim, position 1 — in AND out, so
        # the global microbatch interleaving survives the concatenation
        x_spec = P(None, batch_axis) if batch_axis else P()
        ym = shard_map(
            per_device,
            mesh=mesh,
            in_specs=(spec_params, x_spec),
            out_specs=x_spec,
        )(stacked_params, xm)
        return ym.reshape((B,) + ym.shape[2:])

    return _pipelined


def one_f_one_b(stage_fn, loss_fn, mesh, axis="pp", n_microbatches=None):
    """1F1B pipelined TRAIN step: fn(stacked_params, x, targets) ->
    (loss, stacked_grads).

    Where `gpipe` + jax.grad stashes every microbatch's activations
    (O(M) per stage), this hand-scheduled 1F1B runs each microbatch's
    backward as soon as its forward has cleared the pipe, keeping only a
    circular buffer of 2S-1 in-flight stage inputs (O(S), independent of
    M).  Schedule (per device `s`, microbatch `m`, both slots every tick):

        forward  F(s, m) at tick m + s
        backward B(s, m) at tick m + 2S - 1 - s    (warmup, steady, drain)

    so ticks = M + 2S - 1 and stage s holds at most 2(S-s)-1 in-flight
    microbatches.  stage_fn(params, x_mb) -> y_mb as in `gpipe`;
    loss_fn(y_mb, target_mb) -> scalar (per-microbatch; the step returns
    their mean and grads of that mean).  Gradients accumulate across
    microbatches on each stage's device; the return is a pytree shaped
    like stacked_params (leading stage dim, sharded over `axis`).
    """
    S = mesh.shape[axis]

    def _step(stacked_params, x, targets):
        M = n_microbatches or S
        B = x.shape[0]
        assert B % M == 0, "batch %d must divide microbatches %d" % (B, M)
        mb = B // M
        xm = x.reshape((M, mb) + x.shape[1:])
        tm = targets.reshape((M, mb) + targets.shape[1:])
        buf_n = 2 * S - 1

        def per_device(params, xm_local, tm_local):
            params = jax.tree_util.tree_map(lambda p: p[0], params)
            idx = jax.lax.axis_index(axis)
            ticks = M + 2 * S - 1
            zero = jnp.zeros_like(xm_local[0])
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def vary(v):
                from .mesh import pcast_varying, vma_of

                if axis in vma_of(v):
                    return v  # already device-varying (e.g. from params)
                return pcast_varying(v, axis)

            grad0 = jax.tree_util.tree_map(jnp.zeros_like, params)
            act_buf0 = jnp.zeros((buf_n,) + zero.shape, zero.dtype)

            def tick(carry, t):
                fwd_recv, bwd_recv, act_buf, grad_acc, loss_acc = carry

                # backward residual must be read BEFORE the forward slot
                # writes: for stage 0, B(0, m) and F(0, m + 2S-1) share a
                # tick and a buffer slot (in-flight count == buf size)
                m_b = t - (2 * S - 1 - idx)
                do_b = jnp.logical_and(m_b >= 0, m_b < M)
                slot_b = jnp.clip(m_b, 0, M - 1) % buf_n
                x_res = act_buf[slot_b]

                # ---- forward slot: F(idx, m_f) at t = m_f + idx ----
                m_f = t - idx
                do_f = jnp.logical_and(m_f >= 0, m_f < M)
                inject = xm_local[jnp.clip(m_f, 0, M - 1)]
                x_in = jnp.where(idx == 0, inject, fwd_recv)
                y = stage_fn(params, x_in)
                # stash this stage's input for the microbatch's backward
                slot_f = jnp.clip(m_f, 0, M - 1) % buf_n
                act_buf = jnp.where(
                    do_f, act_buf.at[slot_f].set(x_in), act_buf)
                fwd_send = jax.lax.ppermute(y, axis, fwd_perm)

                # ---- backward slot: B(idx, m_b) at t = m_b + 2S-1-idx ----
                y_res, vjp = jax.vjp(lambda p, xx: stage_fn(p, xx),
                                     params, x_res)
                tgt = tm_local[jnp.clip(m_b, 0, M - 1)]
                loss_mb, dloss = jax.value_and_grad(
                    lambda yy: loss_fn(yy, tgt))(y_res)
                dy = jnp.where(idx == S - 1, dloss / M, bwd_recv)
                dparams, dx = vjp(dy)
                # jnp.where (not a mask multiply) so each leaf keeps its own
                # dtype — mixed-precision params must not promote the carry
                grad_acc = jax.tree_util.tree_map(
                    lambda a, d: jnp.where(do_b, a + d, a), grad_acc, dparams)
                loss_acc = loss_acc + jnp.where(
                    jnp.logical_and(do_b, idx == S - 1), loss_mb, 0.0)
                bwd_send = jax.lax.ppermute(
                    jnp.where(do_b, dx, jnp.zeros_like(dx)), axis, bwd_perm)

                return (fwd_send, bwd_send, act_buf, grad_acc, loss_acc), None

            init = (vary(zero), vary(zero), vary(act_buf0),
                    jax.tree_util.tree_map(vary, grad0),
                    vary(jnp.zeros((), zero.dtype)))
            (_, _, _, grad_acc, loss_acc), _ = jax.lax.scan(
                tick, init, jnp.arange(ticks))
            # mean loss lives on the last stage; broadcast to all
            loss = jax.lax.psum(loss_acc, axis) / M
            grads = jax.tree_util.tree_map(lambda g: g[None], grad_acc)
            return loss, grads

        from .mesh import shard_map

        spec_params = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        return shard_map(
            per_device,
            mesh=mesh,
            in_specs=(spec_params, P(), P()),
            out_specs=(P(), spec_params),
        )(stacked_params, xm, tm)

    return _step


def pipeline_mlp_stages(widths, dtype=jnp.float32):
    """Convenience: equal-width MLP stages for tests/dryrun.  widths is the
    shared layer width; returns (stage_fn, params_list builder output)."""

    def stage_fn(params, x):
        h = jnp.tanh(x @ params["w1"] + params["b1"])
        return h @ params["w2"] + params["b2"]

    def init_stage(k):
        k1, k2 = jax.random.split(k)
        scale = 1.0 / jnp.sqrt(widths)
        return {
            "w1": jax.random.normal(k1, (widths, widths), dtype) * scale,
            "w2": jax.random.normal(k2, (widths, widths), dtype) * scale,
            "b1": jnp.zeros((widths,), dtype),
            "b2": jnp.zeros((widths,), dtype),
        }

    return stage_fn, init_stage


def sequential_reference(stage_fn, params_list, x):
    """Single-device reference: apply stages in order (for parity tests)."""
    for p in params_list:
        x = stage_fn(p, x)
    return x


def pipeline_transformer_stages(d_model, n_head, d_inner=None,
                                dtype=jnp.float32):
    """Transformer-encoder-block stages for pipeline tests/demos: each
    stage is pre-LN self-attention + FFN on [B, T, D] (uniform shapes, so
    stages map onto the `pp` axis like the MLP demo).  Returns
    (stage_fn, init_stage)."""
    d_inner = d_inner or 4 * d_model
    dh = d_model // n_head

    def _ln(x, g, b):
        m = jnp.mean(x, axis=-1, keepdims=True)
        v = jnp.var(x, axis=-1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * g + b

    def stage_fn(p, x):
        h = _ln(x, p["ln1_g"], p["ln1_b"])
        B, T, _ = h.shape
        q = (h @ p["wq"]).reshape(B, T, n_head, dh).transpose(0, 2, 1, 3)
        k = (h @ p["wk"]).reshape(B, T, n_head, dh).transpose(0, 2, 1, 3)
        v = (h @ p["wv"]).reshape(B, T, n_head, dh).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (dh ** -0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        a = jax.nn.softmax(jnp.where(mask[None, None], s, -1e30), axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", a, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, d_model)
        x = x + ctx @ p["wo"]
        h = _ln(x, p["ln2_g"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["w1"]) @ p["w2"]

    def init_stage(key):
        ks = jax.random.split(key, 6)
        s = d_model ** -0.5
        return {
            "wq": jax.random.normal(ks[0], (d_model, d_model), dtype) * s,
            "wk": jax.random.normal(ks[1], (d_model, d_model), dtype) * s,
            "wv": jax.random.normal(ks[2], (d_model, d_model), dtype) * s,
            "wo": jax.random.normal(ks[3], (d_model, d_model), dtype) * s,
            "w1": jax.random.normal(ks[4], (d_model, d_inner), dtype) * s,
            "w2": jax.random.normal(ks[5], (d_inner, d_model), dtype)
                  * (d_inner ** -0.5),
            "ln1_g": jnp.ones((d_model,), dtype),
            "ln1_b": jnp.zeros((d_model,), dtype),
            "ln2_g": jnp.ones((d_model,), dtype),
            "ln2_b": jnp.zeros((d_model,), dtype),
        }

    return stage_fn, init_stage
