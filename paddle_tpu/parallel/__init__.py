"""Parallelism: mesh + sharding rules + collectives.

This package replaces three reference subsystems with one mechanism
(SPMD sharding over a jax Mesh):
- ParallelExecutor's NCCL allreduce graph build (framework/details/,
  multi_devices_graph_pass.cc) -> batch-sharded feeds + replicated params;
  XLA inserts the gradient all-reduce over ICI.
- DistributeTranspiler's program rewrite (transpiler/distribute_transpiler.py)
  -> ShardingRules annotating parameter PartitionSpecs (tensor parallelism,
  sharded embeddings) consumed by DistributedExecutor.
- gen_nccl_id/gRPC bootstrap (distributed_ops/) -> jax.distributed.initialize
  over DCN (collective.init_distributed_env).
"""

from .mesh import make_mesh, default_mesh, mesh_axis_sizes
from .sharding import (ShardingRules, data_parallel_rules,
                       kv_cache_sp_rules, transformer_tp_rules,
                       zero1_rules, zero3_rules)
from .partition_rules import (PartitionRules, TrainPartitionRules,
                              annotate_spmd, current_spmd,
                              partition_rules_for,
                              register_partition_rules,
                              registered_families, spmd_lowering,
                              train_partition_rules_for)
from .executor import DistributedExecutor
from . import ring
from . import ulysses
from . import collective
from . import pipeline
from . import moe
