"""paddle_tpu.parallel"""
