"""Parameter sharding rules — the GSPMD successor to DistributeTranspiler.

The reference rewrites programs: slice params into blocks, route to pservers
(`transpiler/distribute_transpiler.py:239`, slice_variable :80).  Here the
*same program* runs everywhere; a rule list maps parameter names (regex) to
PartitionSpecs, the executor places state with those shardings, and the XLA
SPMD partitioner emits the collectives the transpiler used to hand-insert
(send/recv -> all_gather/reduce_scatter over ICI).
"""

import re

from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "data_parallel_rules",
           "transformer_tp_rules", "kv_cache_sp_rules", "zero1_rules", "zero3_rules", "P"]


class ShardingRules:
    """Ordered (regex, PartitionSpec) list; first match wins; default
    replicated."""

    def __init__(self, rules=None, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in (rules or [])]
        self.default = default

    def spec_for(self, name, ndim=None):
        def guard(spec):
            # rank guard: a spec with more named axes than the value has
            # dims (optimizer beta_pow scalars, 0-d counters) replicates —
            # including when the DEFAULT itself shards (zero3_rules)
            if ndim is not None and len(spec) > ndim:
                return P()
            return spec

        for pat, spec in self.rules:
            if pat.search(name):
                return guard(spec)
        return guard(self.default)

    def sharding_for(self, mesh, name, ndim=None):
        return NamedSharding(mesh, self.spec_for(name, ndim))

    def add(self, pattern, spec):
        self.rules.append((re.compile(pattern), spec))
        return self


def data_parallel_rules():
    """Pure DP: everything replicated; batch dim sharding comes from feeds."""
    return ShardingRules()


def transformer_tp_rules(mp_axis="mp"):
    """Megatron-style tensor parallelism for the transformer model
    (models/transformer.py parameter naming): qkv & ffn-in column-parallel,
    attn-out & ffn-out row-parallel, embeddings vocab-sharded."""
    return ShardingRules(
        [
            (r"mha_[qkv]\.w", P(None, mp_axis)),
            (r"mha_o\.w", P(mp_axis, None)),
            (r"ffn_in\.w", P(None, mp_axis)),
            (r"ffn_in\.b", P(mp_axis)),
            # SwiGLU variant (gpt2 use_swiglu): both gate and up are
            # column-parallel like ffn_in
            (r"ffn_(gate|up)\.w", P(None, mp_axis)),
            (r"ffn_out\.w", P(mp_axis, None)),
            (r"embedding.*\.w|emb\.w", P(mp_axis, None)),
            (r"softmax_out\.w", P(None, mp_axis)),
        ]
    )


def _stack_base(rules, base, inherit_default=True):
    """Append `base`'s rules after `rules`' own (first match wins, so the
    factory's patterns take precedence) and optionally adopt its
    default.  zero3 keeps its OWN sharded default, hence the flag."""
    if base is not None:
        rules.rules = rules.rules + list(base.rules)
        if inherit_default:
            rules.default = base.default
    return rules


def kv_cache_sp_rules(sp_axis="sp", base=None):
    """Distributed KV-cache serving: the decode step programs' per-layer
    `*_{k,v}cache_*` persistables shard their TIME axis over `sp_axis`,
    so a long-context cache that exceeds one chip's HBM spreads across
    the mesh — XLA's SPMD partitioner inserts the attention-merge
    collectives (GSPMD-first; no custom kernel).  Decode parity with the
    unsharded cache is exact (tests/test_parallel.py).  Compose with
    tensor parallelism via `base` (weights on mp, caches on sp)."""
    return _stack_base(
        ShardingRules([(r"_(k|v)cache_\d+$", P(None, None, sp_axis, None))]),
        base)


def zero3_rules(dp_axis="dp", base=None):
    """ZeRO stage-3 capability, declaratively: PARAMETERS (and their
    optimizer state, via the stacked zero1 rules) shard their leading dim
    over the data-parallel axis.  XLA's SPMD partitioner inserts the
    per-use all-gather of each weight and the reduce-scatter of its
    gradient — the collective choreography ZeRO-3 hand-schedules.  The
    executor's divisibility guard keeps small/indivisible tensors
    replicated, so any model compiles.  Compose with TP via `base`.
    """
    rules = zero1_rules(dp_axis)
    # params: anything not matching the accumulator patterns falls through
    # to the default — shard dim 0 over dp (guards replicate misfits);
    # the sharded default deliberately survives composition
    rules.default = P(dp_axis)
    return _stack_base(rules, base, inherit_default=False)


def zero1_rules(dp_axis="dp", base=None):
    """ZeRO stage-1: shard OPTIMIZER STATE over the data-parallel axis
    while parameters stay replicated (or follow `base`'s TP specs).

    Accumulator tensors (moments, velocities, averaged squares — named
    `<param>_<kind>` by Optimizer._add_accumulator) get their leading dim
    sharded over `dp_axis`; the executor's divisibility guard replicates
    any state whose dim 0 doesn't divide, and the rank guard keeps
    `*_pow_acc` scalars replicated.  XLA inserts the gather/scatter
    collectives around the update — the declarative form of ZeRO's
    reduce-scatter + all-gather choreography.
    """
    # the exact Optimizer._add_accumulator kinds (var name is
    # <param>_<kind>_<n>); *_pow_acc scalars are deliberately absent
    state_pats = [
        (r"_(moment[12]?|momentum|velocity|inf_norm|_avg_squared_grad|"
         r"_avg_squared_update|mean_square|mean_grad|squared|linear)"
         r"(_\d+)?$",
         P(dp_axis)),
    ]
    return _stack_base(ShardingRules(state_pats), base)
