"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no long-context story (SURVEY.md §5.7 'Absent'); this is
green-field TPU design: K/V blocks rotate around the `sp` axis ring via
ppermute (one hop per step, riding ICI) while each device holds its local Q
chunk and maintains flash-style running max/denominator — memory O(T_local),
compute overlapped with the rotation by XLA's async collective scheduling.

Use `ring_attention(...)` inside shard_map (see `ring_attention_sharded` for
the wrapped convenience entry).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, scale, bias=None):
    """One q-block x k-block attention piece: returns (scores_max, exp_scores
    @ v, exp row sums) for flash-style merging. q:[B,H,Tq,D] k,v:[B,H,Tk,D]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    pv = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    l = jnp.sum(p, axis=-1)
    return m, pv, l


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard attention with K/V ring rotation.

    q, k, v: local chunks [B, H, T_local, D]; global sequence is the
    concatenation over the `axis_name` ring in axis-index order.
    Returns the local output chunk [B, H, T_local, D].
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    neg = jnp.asarray(-1e30, q.dtype)

    q_pos = my * t_local + jnp.arange(t_local)  # global positions of local q

    def step(i, carry):
        k_blk, v_blk, m_acc, o_acc, l_acc = carry
        src = (my - i) % n  # which rank's block we currently hold
        bias = None
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            bias = jnp.where(mask, 0.0, neg).astype(q.dtype)[None, None]
        m_blk, pv_blk, l_blk = _block_attn(q, k_blk, v_blk, scale, bias)
        # flash merge
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_blk - m_new)
        o_new = o_acc * alpha[..., None] + pv_blk * beta[..., None]
        l_new = l_acc * alpha + l_blk * beta
        # rotate k/v to the next rank (ring over ICI)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m_new, o_new, l_new)

    m0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype)
    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros(q.shape[:-1], q.dtype)
    # static ring length: unrolled python loop (n is a traced constant under
    # shard_map; use fori_loop only when n is dynamic)
    carry = (k, v, m0, o0, l0)
    for i in range(int(n)):
        carry = step(i, carry)
    _, _, m_f, o_f, l_f = carry
    return o_f / l_f[..., None]


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False):
    """Convenience wrapper: shard q/k/v over `axis_name` on the time dim and
    run ring_attention under shard_map.  q,k,v: [B, H, T, D] global."""
    from jax import shard_map

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def inner(ql, kl, vl):
        return ring_attention(ql, kl, vl, axis_name, causal=causal)

    return inner(q, k, v)
