"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has no long-context story (SURVEY.md §5.7 'Absent'); this is
green-field TPU design: K/V blocks rotate around the `sp` axis ring via
ppermute (one hop per step, riding ICI) while each device holds its local Q
chunk and maintains a flash-style running logsumexp — memory O(T_local),
compute overlapped with the rotation by XLA's async collective scheduling.

The ring is a `lax.scan` (HLO size is O(1) in ring size, unlike an
unrolled loop), and each chunk-vs-chunk piece runs through the Pallas
flash kernel when FLAGS_use_pallas is on — so neither the per-chunk
[T_local, T_local] score matrix nor the fwd residuals ever hit HBM.
Differentiable end-to-end (scan + ppermute + custom-vjp flash piece).

Use `ring_attention(...)` inside shard_map (see `ring_attention_sharded`
for the wrapped convenience entry).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ring_attention_sharded"]

from ..ops.pallas_kernels import NEG_INF as _NEG


def _dense_piece(q, k, v, scale, bias=None):
    """One q-chunk x k-chunk attention piece -> (o_norm, lse), f32 lse.
    q:[B,H,Tq,D] k,v:[B,H,Tk,D]; bias broadcastable to [B,H,Tq,Tk]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)) / safe_l[..., None]
    return o, m + jnp.log(safe_l)


def _flash_piece_bhtd(q, k, v, causal, scale, window=0):
    """Pallas flash piece over [B,H,T,D] (kernel wants [BH,T,D])."""
    from ..ops.pallas_kernels import flash_attention_piece

    B, H, T, D = q.shape
    Tk = k.shape[2]
    blk = 128 if (T % 128 == 0 and Tk % 128 == 0) else 8
    o, lse = flash_attention_piece(
        q.reshape(B * H, T, D), k.reshape(B * H, Tk, D),
        v.reshape(B * H, Tk, D), causal, scale, blk, blk, window)
    return (o.astype(jnp.float32).reshape(B, H, T, D),
            lse.reshape(B, H, T))


def _use_flash(t_local, flag=None):
    if flag is None:
        from ..ops.pallas_kernels import use_pallas

        flag = use_pallas()
    return flag and t_local >= 8 and t_local % 8 == 0


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   use_flash=None, window=0):
    """Per-shard attention with K/V ring rotation.

    q, k, v: local chunks [B, H, T_local, D]; global sequence is the
    concatenation over the `axis_name` ring in axis-index order.
    Returns the local output chunk [B, H, T_local, D].

    window > 0 (requires causal): GLOBAL sliding-window attention across
    the ring — each query sees the last `window` global positions, and
    chunks entirely outside every local query's window are skipped
    whole, so per-device compute scales with the window, not the global
    sequence.  (Windowed pieces run on the dense chunk path: the banded
    mask depends on the traced ring offset.)
    """
    window = int(window)
    if window < 0:
        raise ValueError("ring_attention: window must be >= 0")
    if window and not causal:
        raise ValueError("ring_attention: window requires causal=True")
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    t_local = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scale = float(scale)
    flash = _use_flash(t_local, use_flash)
    q_pos = my * t_local + jnp.arange(t_local)  # global positions of local q
    # device-varying types for anything a cond/scan branch must produce
    from .mesh import pcast_varying, vma_of

    vma = tuple(vma_of(q) | {axis_name})

    def skip_piece():
        """A chunk contributing nothing: lse = -1e30 washes out of the
        merge."""
        return (pcast_varying(jnp.zeros(q.shape, jnp.float32), vma),
                pcast_varying(jnp.full(q.shape[:-1], _NEG, jnp.float32),
                              vma))

    def piece(k_blk, v_blk, src):
        """(o, lse) of local q vs the chunk originating at rank `src`."""
        if not causal:
            if flash:
                return _flash_piece_bhtd(q, k_blk, v_blk, False, scale)
            return _dense_piece(q, k_blk, v_blk, scale)
        if flash:
            # src == my: the diagonal chunk — causal within, and the ring
            # offsets cancel so the kernel's LOCAL window mask is exact;
            # src < my: visible (band-masked off-diagonal when windowed —
            # dense, since that mask depends on the traced offset);
            # src > my: fully masked (skipped)
            def offdiag():
                if not window:
                    return _flash_piece_bhtd(q, k_blk, v_blk, False, scale)
                k_pos_od = src * t_local + jnp.arange(t_local)
                m = ((q_pos[:, None] >= k_pos_od[None, :])
                     & (q_pos[:, None] - k_pos_od[None, :] < window))
                bias_od = jnp.where(m, 0.0, _NEG).astype(
                    jnp.float32)[None, None]
                contributes = (my - src - 1) * t_local + 1 < window
                return jax.lax.cond(
                    contributes,
                    lambda: _dense_piece(q, k_blk, v_blk, scale, bias_od),
                    skip_piece,
                )
            return jax.lax.cond(
                src == my,
                lambda: _flash_piece_bhtd(q, k_blk, v_blk, True, scale,
                                          window),
                lambda: jax.lax.cond(src < my, offdiag, skip_piece),
            )
        k_pos = src * t_local + jnp.arange(t_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        if window:
            mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
        bias = jnp.where(mask, 0.0, _NEG).astype(jnp.float32)[None, None]
        if window:
            # skip chunks entirely older than every local query's window:
            # the closest (q, k) pair of chunks (my, src<my) sits
            # (my-src-1)*T_local + 1 positions apart
            contributes = (src == my) | (
                (src < my) & ((my - src - 1) * t_local + 1 < window))
            return jax.lax.cond(
                contributes,
                lambda: _dense_piece(q, k_blk, v_blk, scale, bias),
                skip_piece,
            )
        return _dense_piece(q, k_blk, v_blk, scale, bias)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        k_blk, v_blk, o_acc, lse_acc = carry
        src = (my - i) % n  # which rank's chunk we currently hold
        o_blk, lse_blk = piece(k_blk, v_blk, src)
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        o_new = (o_acc * jnp.exp(lse_acc - lse_new)[..., None]
                 + o_blk * jnp.exp(lse_blk - lse_new)[..., None])
        # rotate k/v to the next rank (ring over ICI)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, o_new, lse_new), None

    # mark the accumulators device-varying over every axis the inputs vary
    # on (the ring axis, plus e.g. a dp axis on a composite mesh) so the
    # scan carry type matches the body output under shard_map
    o0 = pcast_varying(jnp.zeros(q.shape, jnp.float32), vma)
    lse0 = pcast_varying(
        jnp.full(q.shape[:-1], -jnp.inf, jnp.float32), vma)
    (_, _, o_f, _), _ = jax.lax.scan(
        step, (k, v, o0, lse0), jnp.arange(n))
    return o_f.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           use_flash=None, window=0):
    """Convenience wrapper: shard q/k/v over `axis_name` on the time dim and
    run ring_attention under shard_map.  q,k,v: [B, H, T, D] global."""
    from .mesh import shard_map

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def inner(ql, kl, vl):
        return ring_attention(ql, kl, vl, axis_name, causal=causal,
                              use_flash=use_flash, window=window)

    return inner(q, k, v)
