"""Partition-rule registry: persistable var names -> PartitionSpecs.

The GSPMD serving analog of fmengine's ``match_partition_rules`` (and of
the sharding-rule lists `parallel/sharding.py` already feeds the
training-side DistributedExecutor): an ordered (regex, PartitionSpec)
table, FIRST match wins, resolved per var name so a whole model family
— attention qkv/o projections, FFN/SwiGLU weights, embeddings, AND the
serving slot-pool's ``<family>_{k,v}cache_*`` persistables — picks up
tensor-parallel placements with zero per-model edits (the same
no-model-edits discipline as the PR 11 fuse passes).

Differences from ``sharding.ShardingRules`` (kept for the training
paths) that the SERVING pool needs:

- **per-model-family rule tables** (``register_partition_rules`` /
  ``partition_rules_for``): the engine resolves the table from the
  model config's ``partition_family``, so a bert-family pool and a
  gpt2-family pool shard correctly side by side;
- **replicate-by-default that LOGS**: every name that falls through to
  replication is recorded (``replicated_log``) and logged once — a
  silently-replicated KV pool is the failure mode this registry exists
  to make visible;
- **an SPMD lowering context** (``spmd_lowering``/``current_spmd``)
  the op lowerings consult, so ``fused_attention``'s vector-QStart
  branch and ``slot_cache_write`` can wrap their kernels in
  ``shard_map`` / sharding constraints only when a mesh is live.
"""

import logging
import re
import threading
from contextlib import contextmanager

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = [
    "PartitionRules", "TrainPartitionRules", "StageResolution",
    "register_partition_rules",
    "partition_rules_for", "train_partition_rules_for",
    "registered_families", "annotate_spmd", "spmd_lowering",
    "current_spmd", "P",
]

log = logging.getLogger("paddle_tpu.parallel.partition_rules")


class PartitionRules:
    """Ordered (regex, PartitionSpec) table; ``spec_for`` resolves a var
    name (first match wins) with three guards, each of which REPLICATES
    and records why instead of failing:

    - scalar guard: 0-d / 1-element values never shard (SNIPPETS [3]'s
      ``len(leaf.shape) == 0 or prod == 1`` rule);
    - rank guard: a spec with more named axes than the value has dims
      replicates (optimizer counters sharing a param's name prefix);
    - divisibility guard (``sharding_for``, mesh-aware): a dim that
      does not divide by its axis size replicates — a 3-kv-head cache
      on a 2-way mesh must not half-shard.

    Unmatched names fall through to REPLICATED and are logged once per
    name — the registry's contract is that nothing shards silently and
    nothing replicates invisibly."""

    def __init__(self, rules=None, mp_axis="mp"):
        self.mp_axis = mp_axis
        self.rules = [(pat, re.compile(pat), spec)
                      for pat, spec in (rules or [])]
        # (name, reason) for every replicate-fallback decision, in
        # resolution order; dedup'd so steady-state re-resolution of the
        # same scope names does not grow it unboundedly
        self.replicated_log = []
        self._logged = set()

    def add(self, pattern, spec):
        self.rules.append((pattern, re.compile(pattern), spec))
        return self

    def match(self, name):
        """(spec, pattern) of the FIRST rule matching `name`;
        (None, None) when no rule matches."""
        for pat, cre, spec in self.rules:
            if cre.search(name):
                return spec, pat
        return None, None

    def _fallback(self, name, reason):
        if name not in self._logged:
            self._logged.add(name)
            self.replicated_log.append((name, reason))
            log.info("partition_rules: replicating %r (%s)", name, reason)
        return P()

    def spec_for(self, name, shape=None):
        if shape is not None and (
                len(shape) == 0 or int(np.prod(shape)) <= 1):
            # scalar guard — never worth logging (counters, beta_pows)
            return P()
        spec, pat = self.match(name)
        if spec is None:
            return self._fallback(name, "no rule matched")
        if shape is not None and len(spec) > len(shape):
            return self._fallback(
                name, "rank %d < rule %r spec %s" % (len(shape), pat,
                                                     spec))
        return spec

    def sharding_for(self, mesh, name, shape=None):
        """NamedSharding for `name` under `mesh`, applying the
        divisibility guard on top of ``spec_for``."""
        spec = self.spec_for(name, shape)
        if shape is not None and len(spec) > 0:
            from .mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(mesh)
            for dim, axes in zip(shape, tuple(spec)):
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    if int(dim) % int(sizes.get(ax, 1)) != 0:
                        return NamedSharding(mesh, self._fallback(
                            name, "dim %d !%% %s=%d"
                            % (dim, ax, sizes.get(ax, 1))))
        return NamedSharding(mesh, spec)

    def match_table(self, named_shapes):
        """Resolve a whole {name: shape} table at once.  Returns
        (specs dict, replicated list) where `replicated` carries the
        (name, reason) fallbacks from THIS resolution — what the bench
        and the engine surface as 'these stayed replicated'."""
        before = len(self.replicated_log)
        specs = {n: self.spec_for(n, s) for n, s in named_shapes.items()}
        return specs, self.replicated_log[before:]


# ---------------------------------------------------------------------------
# training derived names: grads and optimizer state follow their param
# ---------------------------------------------------------------------------
# <param>@GRAD — the backward.py convention the PR 13 verifier models
_GRAD_SUFFIX = re.compile(r"@GRAD(?:@RENAME@.*)?$")
# <param>_<kind>_<n> — the exact Optimizer._add_accumulator kinds (the
# same list parallel/sharding.py's zero1_rules keys on); *_pow_acc
# scalars are deliberately absent (the scalar guard replicates them)
_ACC_SUFFIX = re.compile(
    r"_(moment[12]?|momentum|velocity|inf_norm|_avg_squared_grad|"
    r"_avg_squared_update|mean_square|mean_grad|squared|linear)"
    r"(_\d+)?$")
# bf16 AMP cast intermediates mirror <var>@RAW_BF16; master params keep
# the param's own name (and therefore its spec) — nothing to strip there
_CAST_SUFFIX = re.compile(r"@RAW_BF16$")


class TrainPartitionRules(PartitionRules):
    """The training extension of the serving rule table: ONE table
    covers params AND every name training derives from them —

    - ``<param>@GRAD`` shards like its param (the partial-sum
      all-reduce the SPMD partitioner emits is the PR 6 allreduce-mean
      on the dp axis of the same mesh);
    - optimizer accumulators ``<param>_<kind>_<n>`` shard like their
      param — ZeRO-style sharded optimizer state as a registry pass
      (``beta*_pow_acc`` [1]-scalars hit the scalar guard and
      replicate, unlogged);
    - bf16 AMP cast mirrors ``<var>@RAW_BF16`` follow the base var;
      f32 master params carry the param's own name, so they keep its
      spec with no extra rule.

    ``dp_axis`` names the data-parallel mesh axis the executor shards
    feed batches over (replicated when absent from the mesh)."""

    def __init__(self, rules=None, mp_axis="mp", dp_axis="dp"):
        super(TrainPartitionRules, self).__init__(rules, mp_axis=mp_axis)
        self.dp_axis = dp_axis

    @staticmethod
    def base_name(name):
        """Strip the derived-name suffixes down to the param name:
        grad first (a grad of a cast is <x>@RAW_BF16@GRAD), then the
        cast mirror, then ONE accumulator suffix."""
        name = _GRAD_SUFFIX.sub("", name)
        name = _CAST_SUFFIX.sub("", name)
        return _ACC_SUFFIX.sub("", name)

    def match(self, name):
        return super(TrainPartitionRules, self).match(self.base_name(name))

    def stage_resolution(self, stage_of_param, n_stages):
        """Stage-scoped resolution for pipeline parallelism: lift this
        table's derived-name discipline (grads / Adam moments / bf16 cast
        mirrors resolve through their param) to stage ownership, so the
        WHOLE optimizer-state family of a param lands on that param's
        pipeline stage.  `stage_of_param` maps raw param names to stage
        ids in [0, n_stages)."""
        return StageResolution(stage_of_param, n_stages)


# Adam's beta-power accumulators are deliberately absent from _ACC_SUFFIX
# (the scalar guard replicates them for GSPMD sharding, so stripping was
# never needed) — stage ownership DOES need them to follow their param.
_POW_SUFFIX = re.compile(r"_beta[12]_pow_acc(_\d+)?$")
# backward.py's un-merged grad contributions (`<p>@GRAD_0`) feed the
# optimizer directly when a param has a single contribution; stage
# ownership must resolve those too, where GSPMD never sees them (grads
# are internal activations there, not placed state)
_GRAD_N_SUFFIX = re.compile(r"@GRAD(_\d+)?(@RENAME@.*)?$")


class StageResolution:
    """Maps params and every training-derived name (grads, Adam moments,
    beta-pow accumulators, bf16 cast mirrors) to a pipeline stage id.
    Names whose base resolves to no known param return None — callers
    treat those as shared/replicated state (learning rate, counters)."""

    def __init__(self, stage_of_param, n_stages):
        self.stage_of_param = dict(stage_of_param)
        self.n_stages = int(n_stages)

    def base_name(self, name):
        name = _GRAD_N_SUFFIX.sub("", name)
        name = _CAST_SUFFIX.sub("", name)
        name = _POW_SUFFIX.sub("", name)
        return _ACC_SUFFIX.sub("", name)

    def stage_for(self, name):
        if name in self.stage_of_param:
            return self.stage_of_param[name]
        return self.stage_of_param.get(self.base_name(name))

    def names_by_stage(self, names):
        """Partition `names` into ([stage0_names, ...], shared_names),
        preserving input order within each bucket."""
        staged = [[] for _ in range(self.n_stages)]
        shared = []
        for n in names:
            s = self.stage_for(n)
            (shared if s is None else staged[s]).append(n)
        return staged, shared


def train_partition_rules_for(family, mp_axis="mp", dp_axis="dp"):
    """The registered family table lifted to TRAINING resolution: the
    same rule list as ``partition_rules_for`` wrapped so grads and
    optimizer state resolve through their param's rule."""
    base = partition_rules_for(family, mp_axis)
    tr = TrainPartitionRules(mp_axis=base.mp_axis, dp_axis=dp_axis)
    tr.rules = list(base.rules)
    return tr


# ---------------------------------------------------------------------------
# per-model-family rule tables
# ---------------------------------------------------------------------------
_FAMILIES = {}


def register_partition_rules(family, factory):
    """Register `factory(mp_axis) -> PartitionRules` for a model family
    (the name models expose as ``Config.partition_family``)."""
    _FAMILIES[family] = factory
    return factory


def registered_families():
    return sorted(_FAMILIES)


def partition_rules_for(family, mp_axis="mp"):
    """The registered rule table for `family`, bound to `mp_axis`."""
    if family not in _FAMILIES:
        raise KeyError(
            "no partition rules registered for model family %r "
            "(known: %s)" % (family, ", ".join(registered_families())))
    return _FAMILIES[family](mp_axis)


def _decoder_rules(mp):
    """The shared decoder-block patterns (transformer.py's param naming,
    reused verbatim by gpt2/bert builders): qkv & ffn-in column-parallel,
    attn-out & ffn-out row-parallel, KV slot-pool on the HEADS axis."""
    return [
        # the learned position table is gathered per position — keep it
        # replicated, and keep this rule BEFORE the emb.w vocab rule
        # (re.search would otherwise match 'emb.w' inside 'pos_emb.w')
        (r"pos_emb\.w", P()),
        (r"mha_[qkv]\.w", P(None, mp)),
        (r"mha_o\.w", P(mp, None)),
        (r"ffn_(in|gate|up)\.w", P(None, mp)),
        (r"ffn_in\.b", P(mp)),
        (r"ffn_out\.w", P(mp, None)),
        # token embedding vocab-sharded: the tied-embedding logits
        # matmul (x @ emb.w^T) then emits vocab-sharded logits, same
        # layout as the untied softmax_out.w below
        (r"emb\.w", P(mp, None)),
        (r"softmax_out\.w", P(None, mp)),
        # the serving slot-pool persistables [B, n_kv, T_max, Dh]:
        # HEADS axis — per-head attention is embarrassingly parallel,
        # so pool bytes/device drop 1/N with zero cross-slot traffic
        (r"_(k|v)cache_\d+$", P(None, mp, None, None)),
    ]


register_partition_rules(
    "gpt2", lambda mp: PartitionRules(_decoder_rules(mp), mp_axis=mp))
register_partition_rules(
    "transformer", lambda mp: PartitionRules(_decoder_rules(mp),
                                             mp_axis=mp))
register_partition_rules(
    "bert", lambda mp: PartitionRules(_decoder_rules(mp), mp_axis=mp))


# ---------------------------------------------------------------------------
# program stamping + the SPMD lowering context
# ---------------------------------------------------------------------------
def annotate_spmd(program, mesh, rules):
    """Stamp `program` for the executor's GSPMD path: persistables
    place per `rules`, the traced step jits with those in/out shardings,
    and the op lowerings see ``current_spmd()`` while tracing.  The
    stamp changes EXECUTION placement only — the program IR is
    untouched (tools/check_program.py verifies the stamped program
    identically to the plain one)."""
    program._spmd = {"mesh": mesh, "rules": rules}
    return program


_SPMD_STATE = threading.local()


@contextmanager
def spmd_lowering(mesh, rules):
    """Bind (mesh, rules) around a trace so op lowerings can emit
    shard_map-wrapped kernels / sharding constraints.  The executor's
    _run_spmd path is the only caller; nesting restores the previous
    binding (a solo-device trace inside a mesh step sees None)."""
    prev = getattr(_SPMD_STATE, "ctx", None)
    _SPMD_STATE.ctx = (mesh, rules)
    try:
        yield
    finally:
        _SPMD_STATE.ctx = prev


def current_spmd():
    """(mesh, rules) when tracing under spmd_lowering, else None."""
    return getattr(_SPMD_STATE, "ctx", None)
