"""DistributedExecutor: run a program over a mesh with sharded state.

Generalizes ParallelExecutor (which is the dp-only special case): feeds are
sharded along the batch dim over the `dp` axis; each state var is placed per
the ShardingRules' PartitionSpec (tensor/model parallelism); XLA SPMD
partitions the single traced step and inserts all collectives over ICI.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import framework
from ..core import scope as scope_mod
from ..core.trace import build_traced_function
from ..executor import as_numpy
from .sharding import ShardingRules

__all__ = ["DistributedExecutor"]



def _np_save(path, arr):
    """npy write that survives non-native dtypes (bfloat16/fp8 round-trip
    as same-width uint views; np.save of ml_dtypes arrays loads back as
    void otherwise)."""
    if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
        arr = arr.view(np.dtype("u%d" % arr.dtype.itemsize))
    np.save(path, arr)


def _np_load(path, dtype_name):
    arr = np.load(path)
    if str(arr.dtype) != dtype_name:
        arr = arr.view(np.dtype(dtype_name))
    return arr




def _quote(name):
    """Collision-free shard-file stem for a var name (percent-encoding:
    'a/b' and 'a_b' must not map to the same file)."""
    from urllib.parse import quote

    return quote(name, safe="")


def _norm_index(idx, shape):
    """Normalize a jax shard index (tuple of slices) to ((start, stop),...)."""
    return tuple(
        (0 if s.start is None else int(s.start),
         dim if s.stop is None else int(s.stop))
        for s, dim in zip(idx, shape)
    )


class _ShardReader:
    """Callable for jax.make_array_from_callback over a shard directory:
    exact index hits read one shard file; mismatched layouts (restore
    onto a different mesh/rules) assemble the full array ONCE with
    coverage validation — a missing shard raises, never zero-fills."""

    def __init__(self, dirname, by_index, shape, dtype):
        self.dirname = dirname
        self.by_index = by_index
        self.shape = shape
        self.dtype = dtype
        self._full = None

    def __call__(self, idx):
        key = _norm_index(idx, self.shape)
        fname = self.by_index.get(key)
        if fname is not None:  # exact shard match (same mesh/rules)
            return _np_load(os.path.join(self.dirname, fname), self.dtype)
        return self.full()[tuple(slice(a, b) for a, b in key)]

    def full(self):
        if self._full is None:
            full = np.zeros(self.shape, np.dtype(self.dtype))
            covered = np.zeros(self.shape, bool) if self.shape else None
            for key, fname in self.by_index.items():
                sl = tuple(slice(a, b) for a, b in key)
                full[sl] = _np_load(
                    os.path.join(self.dirname, fname), self.dtype
                ).reshape(full[sl].shape)
                if covered is not None:
                    covered[sl] = True
            if covered is not None and not covered.all():
                raise IOError(
                    "sharded checkpoint is incomplete: %d of %d elements "
                    "uncovered for shape %s in %s (missing shard files or a "
                    "partial multi-host save)"
                    % (int((~covered).sum()), covered.size, self.shape,
                       self.dirname))
            self._full = full
        return self._full


class DistributedExecutor:
    def __init__(
        self,
        mesh,
        rules=None,
        main_program=None,
        scope=None,
        batch_axis="dp",
        donate=True,
    ):
        self._mesh = mesh
        self._rules = rules or ShardingRules()
        self._program = main_program or framework.default_main_program()
        self._scope = scope or scope_mod.global_scope()
        self._batch_axis = batch_axis if batch_axis in mesh.axis_names else None
        self._donate = donate
        self._cache = {}
        self._step = 0
        self._base_key = jax.random.PRNGKey(self._program.random_seed or 90157)

    def _repl(self):
        return NamedSharding(self._mesh, P())

    def _state_sharding(self, name):
        val = self._scope.find_var(name)
        return self._sharding_for_shape(
            name, getattr(val, "shape", None),
            getattr(val, "ndim", None))

    def _sharding_for_shape(self, name, shape, ndim=None):
        if ndim is None and shape is not None:
            ndim = len(shape)
        spec = self._rules.spec_for(name, ndim)
        # divisibility guard: optimizer scalars and odd-shaped state that
        # share a param's name prefix fall back to replication
        if shape is not None and len(spec) > 0:
            from .mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(self._mesh)
            for dim, axes in zip(shape, tuple(spec)):
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    if dim % sizes.get(ax, 1) != 0:
                        return self._repl()
        return NamedSharding(self._mesh, spec)

    def _batch_sharding(self):
        if self._batch_axis is None:
            return self._repl()
        return NamedSharding(self._mesh, P(self._batch_axis))

    def run(self, fetch_list, feed=None, program=None, return_numpy=True):
        from .mesh import mesh_axis_sizes

        program = program or self._program
        feed = feed or {}
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_list
        ]
        dp_size = (
            mesh_axis_sizes(self._mesh).get(self._batch_axis, 1)
            if self._batch_axis
            else 1
        )
        feed_arrays = {}
        for name, value in feed.items():
            arr = jnp.asarray(np.asarray(value))
            if arr.ndim and dp_size > 1 and arr.shape[0] % dp_size == 0:
                feed_arrays[name] = jax.device_put(arr, self._batch_sharding())
            else:
                feed_arrays[name] = jax.device_put(arr, self._repl())
        feed_sig = tuple(
            sorted((n, tuple(a.shape), str(a.dtype)) for n, a in feed_arrays.items())
        )
        key_id = (id(program), program._version, feed_sig, tuple(fetch_names))
        hit = self._cache.get(key_id)
        if hit is None:
            feed_names = tuple(n for n, _, _ in feed_sig)
            traced = build_traced_function(
                program, 0, feed_names, fetch_names, self._scope
            )
            ro_sh = {n: self._state_sharding(n) for n in traced.ro_names}
            rw_sh = {n: self._state_sharding(n) for n in traced.rw_names}
            out_state_sh = {n: self._state_sharding(n) for n in traced.updated}
            jitted = jax.jit(
                traced.fn,
                in_shardings=(
                    {n: feed_arrays[n].sharding for n in feed_arrays},
                    ro_sh,
                    rw_sh,
                    self._repl(),
                ),
                out_shardings=(None, out_state_sh),
                donate_argnums=(2,) if self._donate else (),
            )
            hit = (traced, jitted)
            self._cache[key_id] = hit
        traced, jitted = hit
        ro_state = {
            n: jax.device_put(self._scope.find_var(n), self._state_sharding(n))
            for n in traced.ro_names
        }
        rw_state = {
            n: jax.device_put(self._scope.find_var(n), self._state_sharding(n))
            for n in traced.rw_names
        }
        rng = jax.random.fold_in(self._base_key, self._step)
        self._step += 1
        with self._mesh:
            fetches, new_state = jitted(feed_arrays, ro_state, rw_state, rng)
        for n, v in new_state.items():
            self._scope.set(n, v)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    # ---- sharded checkpointing (ICI-path analog of the pserver shard
    # checkpoints, distributed/ps_server.py; at v5e-64 scale a gather-to-
    # host-then-save round trip is neither feasible nor necessary) ------
    def save_sharded(self, dirname, var_names=None):
        """Write each persistable var as its ADDRESSABLE device shards
        plus a per-process index file — no full-array gather on the host.

        Multi-host layout: every process writes `index.<pid>.json` and
        shard files carrying its process id (`<var>.p<pid>.shardK.npy`),
        so concurrent savers never collide; load_sharded merges all
        index files.  Restore validates full coverage."""
        import json
        import os

        os.makedirs(dirname, exist_ok=True)
        pid = jax.process_index()
        if var_names is None:
            from ..io import get_program_persistable_vars

            var_names = [
                v.name for v in get_program_persistable_vars(self._program)
            ]
        index = {}
        for name in var_names:
            val = self._scope.find_var(name)
            if val is None:
                continue
            arr = jnp.asarray(val)
            entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                     "shards": []}

            def _add(key, data, _entry=entry, _name=name):
                fname = "%s.p%d.shard%d.npy" % (
                    _quote(_name), pid, len(_entry["shards"]))
                _np_save(os.path.join(dirname, fname), data)
                _entry["shards"].append(
                    {"file": fname, "index": [list(k) for k in key]})

            shards = getattr(arr, "addressable_shards", None)
            if not shards:  # plain numpy/replicated host value
                _add(tuple((0, d) for d in arr.shape), np.asarray(arr))
            else:
                seen = set()
                for shard in shards:
                    key = _norm_index(shard.index, arr.shape)
                    if key in seen:  # replicated across an axis: save once
                        continue
                    seen.add(key)
                    _add(key, np.asarray(shard.data))
            index[name] = entry
        with open(os.path.join(dirname, "index.%d.json" % pid), "w") as f:
            json.dump(index, f)
        return sorted(index)

    def load_sharded(self, dirname):
        """Restore a save_sharded checkpoint into the scope under the
        CURRENT mesh/rules.  Shards matching the target sharding load
        directly device-by-device; on a mesh/rule change the var is
        assembled host-side from its shards and re-placed (resharding
        restore).  Incomplete checkpoints (missing shards) raise instead
        of restoring silently-zeroed weights."""
        import glob
        import json
        import os

        paths = sorted(glob.glob(os.path.join(dirname, "index.*.json")))
        if not paths:  # pre-multihost-layout checkpoints
            paths = [os.path.join(dirname, "index.json")]
        index = {}
        for p in paths:
            with open(p) as f:
                for name, entry in json.load(f).items():
                    if name in index:
                        index[name]["shards"].extend(entry["shards"])
                    else:
                        index[name] = entry
        for name, entry in index.items():
            shape = tuple(entry["shape"])
            dtype = entry["dtype"]
            by_index = {
                tuple(tuple(ix) for ix in s["index"]): s["file"]
                for s in entry["shards"]
            }
            reader = _ShardReader(dirname, by_index, shape, dtype)
            if not shape:
                self._scope.set(
                    name,
                    jax.device_put(reader.full().reshape(()), self._repl()),
                )
                continue
            sharding = self._sharding_for_shape(name, shape)
            arr = jax.make_array_from_callback(shape, sharding, reader)
            self._scope.set(name, arr)
        return sorted(index)
