"""DistributedExecutor: run a program over a mesh with sharded state.

Generalizes ParallelExecutor (which is the dp-only special case): feeds are
sharded along the batch dim over the `dp` axis; each state var is placed per
the ShardingRules' PartitionSpec (tensor/model parallelism); XLA SPMD
partitions the single traced step and inserts all collectives over ICI.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import framework
from ..core import scope as scope_mod
from ..core.trace import build_traced_function
from ..executor import as_numpy
from .sharding import ShardingRules

__all__ = ["DistributedExecutor"]


class DistributedExecutor:
    def __init__(
        self,
        mesh,
        rules=None,
        main_program=None,
        scope=None,
        batch_axis="dp",
        donate=True,
    ):
        self._mesh = mesh
        self._rules = rules or ShardingRules()
        self._program = main_program or framework.default_main_program()
        self._scope = scope or scope_mod.global_scope()
        self._batch_axis = batch_axis if batch_axis in mesh.axis_names else None
        self._donate = donate
        self._cache = {}
        self._step = 0
        self._base_key = jax.random.PRNGKey(self._program.random_seed or 90157)

    def _repl(self):
        return NamedSharding(self._mesh, P())

    def _state_sharding(self, name):
        val = self._scope.find_var(name)
        ndim = getattr(val, "ndim", None)
        spec = self._rules.spec_for(name, ndim)
        # divisibility guard: optimizer scalars and odd-shaped state that
        # share a param's name prefix fall back to replication
        shape = getattr(val, "shape", None)
        if shape is not None and len(spec) > 0:
            from .mesh import mesh_axis_sizes

            sizes = mesh_axis_sizes(self._mesh)
            for dim, axes in zip(shape, tuple(spec)):
                if axes is None:
                    continue
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    if dim % sizes.get(ax, 1) != 0:
                        return self._repl()
        return NamedSharding(self._mesh, spec)

    def _batch_sharding(self):
        if self._batch_axis is None:
            return self._repl()
        return NamedSharding(self._mesh, P(self._batch_axis))

    def run(self, fetch_list, feed=None, program=None, return_numpy=True):
        from .mesh import mesh_axis_sizes

        program = program or self._program
        feed = feed or {}
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v) for v in fetch_list
        ]
        dp_size = (
            mesh_axis_sizes(self._mesh).get(self._batch_axis, 1)
            if self._batch_axis
            else 1
        )
        feed_arrays = {}
        for name, value in feed.items():
            arr = jnp.asarray(np.asarray(value))
            if arr.ndim and dp_size > 1 and arr.shape[0] % dp_size == 0:
                feed_arrays[name] = jax.device_put(arr, self._batch_sharding())
            else:
                feed_arrays[name] = jax.device_put(arr, self._repl())
        feed_sig = tuple(
            sorted((n, tuple(a.shape), str(a.dtype)) for n, a in feed_arrays.items())
        )
        key_id = (id(program), program._version, feed_sig, tuple(fetch_names))
        hit = self._cache.get(key_id)
        if hit is None:
            feed_names = tuple(n for n, _, _ in feed_sig)
            traced = build_traced_function(
                program, 0, feed_names, fetch_names, self._scope
            )
            ro_sh = {n: self._state_sharding(n) for n in traced.ro_names}
            rw_sh = {n: self._state_sharding(n) for n in traced.rw_names}
            out_state_sh = {n: self._state_sharding(n) for n in traced.updated}
            jitted = jax.jit(
                traced.fn,
                in_shardings=(
                    {n: feed_arrays[n].sharding for n in feed_arrays},
                    ro_sh,
                    rw_sh,
                    self._repl(),
                ),
                out_shardings=(None, out_state_sh),
                donate_argnums=(2,) if self._donate else (),
            )
            hit = (traced, jitted)
            self._cache[key_id] = hit
        traced, jitted = hit
        ro_state = {
            n: jax.device_put(self._scope.find_var(n), self._state_sharding(n))
            for n in traced.ro_names
        }
        rw_state = {
            n: jax.device_put(self._scope.find_var(n), self._state_sharding(n))
            for n in traced.rw_names
        }
        rng = jax.random.fold_in(self._base_key, self._step)
        self._step += 1
        with self._mesh:
            fetches, new_state = jitted(feed_arrays, ro_state, rw_state, rng)
        for n, v in new_state.items():
            self._scope.set(n, v)
        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)
