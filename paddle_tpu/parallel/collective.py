"""Distributed bootstrap + collective helpers.

The reference bootstraps NCCL with an ad-hoc gRPC broadcast of the unique id
(distributed_ops/gen_nccl_id_op.cc:31) and wires multi-node ranks through
env vars (PADDLE_TRAINER_ID etc.).  TPU-natively the whole thing is
jax.distributed.initialize over DCN; the same env-var contract is honored so
reference launch scripts keep working.
"""

import os

import jax

__all__ = [
    "init_distributed_env",
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "broadcast",
    "barrier",
    "trainer_id",
    "num_trainers",
]


def trainer_id():
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("TRAINER_ID", 0)))


def num_trainers():
    return int(os.environ.get("PADDLE_TRAINERS", os.environ.get("TRAINERS", 1)))


def init_distributed_env(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host bootstrap (gen_nccl_id + NCCLContextMap analog).

    coordinator defaults from PADDLE_PSERVER_IPS/PADDLE_CURRENT_IP-style env
    or JAX defaults; call once per host before building executors."""
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        if eps:
            coordinator_address = eps.split(",")[0]
    if num_processes is None:
        num_processes = num_trainers()
    if process_id is None:
        process_id = trainer_id()
    if num_processes <= 1:
        return  # single-process: nothing to do
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


# thin named wrappers so user kernels/shard_map bodies read like the
# reference's collective vocabulary
def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name, src=0):
    idx = jax.lax.axis_index(axis_name)
    import jax.numpy as jnp

    sel = (idx == src).astype(x.dtype)
    return jax.lax.psum(x * sel, axis_name)


def barrier(axis_name):
    jax.lax.psum(1, axis_name)
