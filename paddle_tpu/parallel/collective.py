"""Distributed bootstrap + collective helpers.

The reference bootstraps NCCL with an ad-hoc gRPC broadcast of the unique id
(distributed_ops/gen_nccl_id_op.cc:31) and wires multi-node ranks through
env vars (PADDLE_TRAINER_ID etc.).  TPU-natively the whole thing is
jax.distributed.initialize over DCN; the same env-var contract is honored so
reference launch scripts keep working.
"""

import contextlib
import os
import threading

import jax

__all__ = [
    "init_distributed_env",
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "broadcast",
    "barrier",
    "trainer_id",
    "num_trainers",
    "collective_lowering",
    "lowering_axis",
]


def trainer_id():
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("TRAINER_ID", 0)))


def num_trainers():
    return int(os.environ.get("PADDLE_TRAINERS", os.environ.get("TRAINERS", 1)))


def _enable_cpu_cross_process_collectives():
    """Multi-process SPMD on the CPU backend needs an explicit
    cross-process collectives implementation (gloo over TCP) — without it
    XLA rejects the computation outright ("Multiprocess computations
    aren't implemented on the CPU backend").  Must run BEFORE the backend
    initializes; harmless on jax builds without the knob or on TPU."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover - jax version
        pass


def init_distributed_env(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host bootstrap (gen_nccl_id + NCCLContextMap analog).

    coordinator defaults from PADDLE_PSERVER_IPS/PADDLE_CURRENT_IP-style env
    or JAX defaults; call once per host before building executors."""
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        if eps:
            coordinator_address = eps.split(",")[0]
    if num_processes is None:
        num_processes = num_trainers()
    if process_id is None:
        process_id = trainer_id()
    if num_processes <= 1:
        return  # single-process: nothing to do
    _enable_cpu_cross_process_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


# thin named wrappers so user kernels/shard_map bodies read like the
# reference's collective vocabulary
def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name, src=0):
    idx = jax.lax.axis_index(axis_name)
    import jax.numpy as jnp

    sel = (idx == src).astype(x.dtype)
    return jax.lax.psum(x * sel, axis_name)


def barrier(axis_name):
    jax.lax.psum(1, axis_name)


# ---- collective-lowering context ----------------------------------------
# The op registry's collective lowerings (ops/collective_ops.py
# c_allreduce_*) need to know, AT TRACE TIME, whether a mesh axis is bound
# around the traced step — psum over an unbound axis is a NameError, and a
# transpiled collective program must still degrade to single-replica
# semantics (allreduce == identity) when run on a plain executor.  The
# collective run path (executor._run_collective) enters this context while
# tracing the step under shard_map; lowering rules consult lowering_axis().
# Thread-local: pserver threads in in-process tests trace their shard
# programs concurrently with a collective trainer trace.
_lowering_state = threading.local()


@contextlib.contextmanager
def collective_lowering(axis_name, nranks):
    """Bind `axis_name` (size `nranks`) for collective op lowerings during
    a trace.  Nesting replaces (the inner trace wins, e.g. a pserver-side
    trace inside a host callback must NOT see the trainer's axis)."""
    prev = getattr(_lowering_state, "axis", None)
    _lowering_state.axis = (str(axis_name), int(nranks))
    try:
        yield
    finally:
        _lowering_state.axis = prev


def lowering_axis():
    """(axis_name, nranks) bound by the active collective trace, or None
    when tracing outside a collective run (single-replica semantics)."""
    return getattr(_lowering_state, "axis", None)
