"""Transformer (base config) — encoder/decoder for WMT-style seq2seq.

Capability mirror of the reference's benchmark transformer
(`python/paddle/fluid/tests/unittests/dist_transformer.py:123`
ModelHyperParams / transformer builder), re-designed for TPU: fixed-length
padded batches with explicit attention masks (no LoD), all attention heads
batched into single MXU matmuls, and the whole train step compiled as one
XLA program.  Tensor-parallel sharding rules for the qkv/ffn weights live in
paddle_tpu.parallel (GSPMD replaces the DistributeTranspiler).
"""

import numpy as np

from .. import layers, unique_name
from ..initializer import Normal
from ..param_attr import ParamAttr


def _pa(base):
    """Named ParamAttr so parallel.transformer_tp_rules can target these
    weights by regex (the GSPMD analog of the transpiler's param slicing)."""
    return ParamAttr(name=unique_name.generate(base))

__all__ = [
    "ModelHyperParams",
    "transformer",
    "wmt_transformer_program",
    "transformer_logits_program",
    "greedy_translate",
    "greedy_translate_cached",
    "beam_translate_cached",
    "sample_translate_cached",
    "transformer_decode_programs",
    "force_decode_logits_cached",
    "beam_translate",
]


class ModelHyperParams:
    """Transformer-base (dist_transformer.py ModelHyperParams parity)."""

    src_vocab_size = 10000
    trg_vocab_size = 10000
    max_length = 256
    d_model = 512
    d_inner_hid = 2048
    n_head = 8
    n_layer = 6
    dropout = 0.1
    label_smooth_eps = 0.1
    recompute = False  # rematerialize each enc/dec layer in backward
    partition_family = "transformer"


def _pos_encoding_table(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    i = np.arange(d_model)[None, :].astype("float64")
    angle = pos / np.power(10000, 2 * (i // 2) / d_model)
    table = np.zeros((max_len, d_model), dtype="float32")
    table[:, 0::2] = np.sin(angle[:, 0::2])
    table[:, 1::2] = np.cos(angle[:, 1::2])
    return table


def prepare_embedding(ids, vocab_size, d_model, max_len, dropout_rate, pos_name, is_test=False):
    """Word + sinusoid position embedding (the reference's
    prepare_encoder/decoder), position table as a frozen parameter."""
    word_emb = layers.embedding(
        ids,
        size=[vocab_size, d_model],
        param_attr=ParamAttr(initializer=Normal(0.0, d_model ** -0.5)),
    )
    word_emb = layers.scale(word_emb, scale=d_model ** 0.5)
    pos_table = layers.create_parameter(
        shape=[max_len, d_model],
        dtype="float32",
        name=pos_name,
        default_initializer=None,
        attr=ParamAttr(
            name=pos_name,
            trainable=False,
            initializer=_NumpyInit(_pos_encoding_table(max_len, d_model)),
        ),
    )
    seq_len = ids.shape[1]
    pos_slice = layers.slice(pos_table, axes=[0], starts=[0], ends=[seq_len])
    out = layers.elementwise_add(word_emb, pos_slice, axis=1)
    if dropout_rate:
        out = layers.dropout(out, dropout_rate, is_test=is_test)
    return out


class _NumpyInit:
    def __init__(self, value):
        self.value = value

    def __call__(self, var, block):
        from ..initializer import NumpyArrayInitializer

        return NumpyArrayInitializer(self.value)(var, block)


def multi_head_attention(
    queries, keys, values, attn_bias, d_model, n_head, dropout_rate=0.0,
    is_test=False, cache=None, fused=False, kpad_bias=None, causal=False,
    n_kv_head=None, rotary=False,
):
    """All heads in one qkv projection + batched matmuls (MXU-shaped).
    attn_bias: [B, 1 or H, Tq, Tk] additive mask (−1e9 at masked slots).

    fused=True routes through the fused_attention op (flash kernel under
    FLAGS_use_pallas, fused XLA otherwise): padding is expressed as the
    rank-1 kpad_bias [B, Tk] and causality as a flag, so the [Tq, Tk]
    score matrix never hits HBM.  Attention-prob dropout is folded away on
    this path (the probs are never materialized) — standard flash-attention
    practice; residual/ffn dropout still applies.

    n_kv_head < n_head enables grouped-query attention (MQA at 1): k/v
    project to n_kv_head heads shared by n_head/n_kv_head query groups.
    On the cached decode path the KV cache AND the per-step K/V reads
    shrink by that factor (query groups fold onto the length-1 time
    axis, no tiling).  On the training paths the kv heads are broadcast
    back to n_head before attention — there the win is parameters and
    kv-projection FLOPs, not attention reads.

    rotary=True applies rotary position embedding (RoPE) to q and k after
    the head split — full-sequence positions arange(T), or the cache's
    current position on the decode path (cached keys store pre-rotated,
    so relative rotations stay exact across steps).

    RAGGED cache mode (the continuous-batching serving step): a cache
    dict carrying "pos_rows" [B] + "width_rows" [B] (and "pos_mat"
    [B, W] under rotary) instead of the scalar "pos" writes each batch
    row's K/V at ITS OWN position with ITS OWN valid width
    (slot_cache_write: a decoding slot writes 1 token, a prefilling
    slot a chunk, a free slot nothing) and masks attention with
    per-row offset-causal cutoffs (fused_attention vector qstart) —
    one dispatch serves a pool of requests at heterogeneous
    positions."""
    dh = d_model // n_head
    n_kv = n_kv_head or n_head
    if n_head % n_kv:
        raise ValueError(
            "n_kv_head (%d) must divide n_head (%d)" % (n_kv, n_head))
    q = layers.fc(queries, size=d_model, num_flatten_dims=2, bias_attr=False,
                  param_attr=_pa("mha_q.w"))
    k = layers.fc(keys, size=n_kv * dh, num_flatten_dims=2, bias_attr=False,
                  param_attr=_pa("mha_k.w"))
    v = layers.fc(values, size=n_kv * dh, num_flatten_dims=2, bias_attr=False,
                  param_attr=_pa("mha_v.w"))

    def split_heads(x, heads):
        b, t = x.shape[0], x.shape[1]
        x = layers.reshape(x, [b, t, heads, dh])
        return layers.transpose(x, [0, 2, 1, 3])  # [B, heads, T, Dh]

    def repeat_kv(x):
        """[B, n_kv, T, Dh] -> [B, n_head, T, Dh]: each kv head serves a
        contiguous group of query heads."""
        if n_kv == n_head:
            return x
        g = n_head // n_kv
        b, _, t, _ = x.shape
        x = layers.reshape(x, [b, n_kv, 1, t, dh])
        x = layers.expand(x, [1, 1, g, 1, 1])
        return layers.reshape(x, [b, n_head, t, dh])

    q = split_heads(q, n_head)
    k, v = split_heads(k, n_kv), split_heads(v, n_kv)
    if rotary:
        # ragged serving feeds pos_mat [B, W] (per-row positions);
        # chunked decode feeds pos_vec (positions pos..pos+W-1); the
        # one-token step feeds the scalar pos
        rpos = None
        if cache is not None:
            if "pos_rows" in cache and "pos_mat" not in cache:
                raise ValueError(
                    "ragged cached attention with rotary needs pos_mat "
                    "(per-row absolute positions [B, W]) — without it "
                    "every slot would silently rotate at arange(W)")
            for key in ("pos_mat", "pos_vec", "pos"):
                if key in cache:
                    rpos = cache[key]
                    break
            if rpos is None:
                raise KeyError(
                    "cached rotary attention needs pos/pos_vec/pos_mat")
        q = layers.rotary_embed(q, pos=rpos)
        k = layers.rotary_embed(k, pos=rpos)
    if cache is not None:
        if attn_bias is not None or kpad_bias is not None:
            raise ValueError(
                "cached attention owns its <=pos mask; attn_bias/kpad_bias "
                "are not supported on the cache path")
        if causal:
            raise ValueError(
                "cached attention handles causality via the cache mask — "
                "pass causal=False with cache")
        if dropout_rate:
            raise ValueError("cached decode is inference-only: "
                             "dropout_rate must be 0")
        # incremental KV-cached decode: q/k/v are the ONE current token's
        # projections; k/v land in the [B, H, T_max, Dh] cache vars at
        # cache["pos"], and q attends over the cache with a <=pos mask.
        # The cache vars are persistable scope state — the executor's
        # functionalization threads the update back (donated in HBM).
        from ..layer_helper import LayerHelper

        helper = LayerHelper("cached_attention")
        ragged = "pos_rows" in cache
        if ragged and "width_rows" not in cache:
            raise ValueError(
                "ragged cached attention needs width_rows alongside "
                "pos_rows (per-row valid write widths)")

        def write_cache(cvar, new):
            """Updated full-length cache tensor; also assigns it back into
            the persistable var (state threads through the executor)."""
            if ragged:
                out = layers.slot_cache_write(
                    cvar, new, cache["pos_rows"], cache["width_rows"])
            else:
                out = helper.create_variable_for_type_inference(cvar.dtype)
                helper.append_op(
                    "seq_cache_write",
                    inputs={"Cache": [cvar], "New": [new],
                            "Pos": [cache["pos"]]},
                    outputs={"Out": [out]},
                )
            helper.append_op("assign", inputs={"X": [out]},
                             outputs={"Out": [cvar]})
            return out

        if int(cache["k"].shape[1]) != n_kv:
            raise ValueError(
                "cache has %d kv heads but n_kv_head is %d — create the "
                "caches with the model's kv head count"
                % (int(cache["k"].shape[1]), n_kv))
        k_full = write_cache(cache["k"], k)
        v_full = write_cache(cache["v"], v)
        t_max = int(cache["k"].shape[2])
        bsz = int(cache["k"].shape[0])
        width = int(q.shape[2])
        def pos_bias():
            # one-token steps mask via the rank-1 <=pos key bias
            bias = helper.create_variable_for_type_inference("float32")
            helper.append_op(
                "decode_pos_mask", inputs={"Pos": [cache["pos"]]},
                outputs={"Out": [bias]}, attrs={"t_max": t_max, "batch": bsz},
            )
            return bias

        if ragged:
            # RAGGED step: every row carries its own global query base
            # (pos_rows), so the offset-causal mask is per-row — one
            # dispatch mixes prefill chunks with one-token decodes.  GQA
            # tiles K/V back to n_head (same accepted tradeoff as the
            # chunked step: per-row cutoffs cannot share the time axis
            # with the query-group fold).
            ctx = layers.fused_attention(
                q, repeat_kv(k_full), repeat_kv(v_full), causal=True,
                qstart=cache["pos_rows"], scale=dh ** -0.5,
            )  # [B, H, W, Dh]
        elif width > 1:
            # CHUNKED decode/prefill: W queries at global positions
            # pos..pos+W-1 against the whole cache — offset-causal
            # masking (fused_attention qstart) gives each chunk row its
            # own cutoff, so one dispatch fills W cache slots.  GQA
            # tiles K/V back to n_head here (accepted tradeoff: the
            # one-token group fold puts the g query heads on the time
            # axis, which cannot carry W per-row causal cutoffs at the
            # same time; chunked steps are compute-bound MXU work, so
            # the n_head/n_kv-fold cache read costs little where the
            # fold matters most — the HBM-bound one-token step keeps it)
            ctx = layers.fused_attention(
                q, repeat_kv(k_full), repeat_kv(v_full), causal=True,
                qstart=cache["pos"], scale=dh ** -0.5,
            )  # [B, H, W, Dh]
        elif n_kv == n_head:
            ctx = layers.fused_attention(
                q, k_full, v_full, bias=pos_bias(), causal=False,
                scale=dh ** -0.5,
            )  # [B, H, 1, Dh]
        else:
            # GQA decode WITHOUT tiling K/V back to n_head: the g query
            # heads of a group all attend the same kv head, so fold the
            # group onto the (length-1) query-time axis — heads = n_kv,
            # Tq = g.  The rank-1 key bias broadcasts over the g rows;
            # per-step K/V reads really are n_kv-sized.
            g = n_head // n_kv
            q_g = layers.reshape(q, [bsz, n_kv, g, dh])
            ctx = layers.fused_attention(
                q_g, k_full, v_full, bias=pos_bias(), causal=False,
                scale=dh ** -0.5,
            )  # [B, n_kv, g, Dh]
            ctx = layers.reshape(ctx, [bsz, n_head, 1, dh])
    elif fused:
        if attn_bias is not None and kpad_bias is None:
            raise ValueError(
                "fused attention cannot consume the dense [B,H,Tq,Tk] "
                "attn_bias — pass its rank-1 key-padding row as kpad_bias "
                "(plus causal=True for decoder self-attention) or use "
                "fused=False"
            )
        ctx = layers.fused_attention(
            q, repeat_kv(k), repeat_kv(v), bias=kpad_bias, causal=causal,
            scale=dh ** -0.5
        )  # [B, H, Tq, Dh]
    else:
        k, v = repeat_kv(k), repeat_kv(v)
        product = layers.matmul(q, k, transpose_y=True, alpha=dh ** -0.5)
        if attn_bias is not None:
            product = layers.elementwise_add(product, attn_bias)
        weights = layers.softmax(product)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_rate, is_test=is_test)
        ctx = layers.matmul(weights, v)  # [B, H, Tq, Dh]
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    b, t = ctx.shape[0], ctx.shape[1]
    ctx = layers.reshape(ctx, [b, t, d_model])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2, bias_attr=False,
                     param_attr=_pa("mha_o.w"))


def positionwise_ffn(x, d_inner, d_model, dropout_rate=0.0, is_test=False):
    hidden = layers.fc(x, size=d_inner, num_flatten_dims=2, act="relu",
                       param_attr=_pa("ffn_in.w"), bias_attr=_pa("ffn_in.b"))
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_rate, is_test=is_test)
    return layers.fc(hidden, size=d_model, num_flatten_dims=2,
                     param_attr=_pa("ffn_out.w"))


def pre_post_process(prev, out, dropout_rate=0.0, is_test=False):
    """residual add + layer_norm (the reference's post_process_layer 'dan')."""
    if dropout_rate:
        out = layers.dropout(out, dropout_rate, is_test=is_test)
    added = layers.elementwise_add(prev, out)
    return layers.layer_norm(added, begin_norm_axis=2)


def encoder_layer(x, attn_bias, hp, is_test=False, kpad_bias=None):
    fused = getattr(hp, "fused_attn", False)
    attn = multi_head_attention(
        x, x, x, attn_bias, hp.d_model, hp.n_head, hp.dropout, is_test,
        fused=fused, kpad_bias=kpad_bias,
    )
    x = pre_post_process(x, attn, hp.dropout, is_test)
    ffn = positionwise_ffn(x, hp.d_inner_hid, hp.d_model, hp.dropout, is_test)
    return pre_post_process(x, ffn, hp.dropout, is_test)


def decoder_layer(x, enc_out, self_bias, cross_bias, hp, is_test=False,
                  self_kpad=None, cross_kpad=None, cache=None):
    """With `cache` ({"k","v","pos"}), x is ONE current target token:
    self-attention runs KV-cached (same machinery as gpt2's decode step)
    and cross-attention attends the full enc_out with a one-token query.
    The SAME function builds training and decode-step graphs, so the
    parameter-creation order (weight sharing by name) holds by
    construction."""
    fused = getattr(hp, "fused_attn", False)
    self_attn = multi_head_attention(
        x, x, x, self_bias if cache is None else None, hp.d_model,
        hp.n_head, 0.0 if cache is not None else hp.dropout, is_test,
        fused=fused or cache is not None,
        kpad_bias=self_kpad if cache is None else None,
        causal=fused and cache is None, cache=cache,
    )
    x = pre_post_process(x, self_attn, hp.dropout, is_test)
    cross = multi_head_attention(
        x, enc_out, enc_out, cross_bias, hp.d_model, hp.n_head,
        0.0 if cache is not None else hp.dropout, is_test,
        fused=fused or cache is not None, kpad_bias=cross_kpad,
    )
    x = pre_post_process(x, cross, hp.dropout, is_test)
    ffn = positionwise_ffn(x, hp.d_inner_hid, hp.d_model, hp.dropout, is_test)
    return pre_post_process(x, ffn, hp.dropout, is_test)


def transformer(
    src_ids, trg_ids, src_slf_attn_bias, trg_slf_attn_bias, trg_src_attn_bias,
    hp=ModelHyperParams, is_test=False, trg_kpad_bias=None
):
    """Full encoder-decoder; returns [B, Tt, trg_vocab] logits.

    When hp.fused_attn is set, attention runs through the fused_attention
    op: the rank-1 key-padding rows are derived in-graph from the
    [B, 1, 1, Tk] bias feeds (same feed contract), and decoder causality
    comes from the kernel's causal flag instead of the dense
    trg_slf_attn_bias — which requires trg_kpad_bias ([B, Tt], e.g. built
    from the token-weight feed) since the dense [B, 1, Tt, Tt] bias cannot
    be passed rank-1."""
    fused = getattr(hp, "fused_attn", False)
    src_kpad = cross_kpad = None
    if fused:
        src_len = int(src_slf_attn_bias.shape[-1])
        src_kpad = layers.reshape(src_slf_attn_bias, [-1, src_len])
        cross_kpad = layers.reshape(trg_src_attn_bias, [-1, src_len])
        if trg_kpad_bias is None:
            raise ValueError("hp.fused_attn requires trg_kpad_bias")
    enc_in = prepare_embedding(
        src_ids, hp.src_vocab_size, hp.d_model, hp.max_length, hp.dropout,
        "src_pos_enc_table", is_test,
    )
    remat = getattr(hp, "recompute", False) and not is_test
    x = enc_in
    for _ in range(hp.n_layer):
        if remat:
            x = layers.recompute(
                lambda h: encoder_layer(h, src_slf_attn_bias, hp, is_test,
                                        kpad_bias=src_kpad), x)
        else:
            x = encoder_layer(x, src_slf_attn_bias, hp, is_test,
                              kpad_bias=src_kpad)
    enc_out = x

    dec_in = prepare_embedding(
        trg_ids, hp.trg_vocab_size, hp.d_model, hp.max_length, hp.dropout,
        "trg_pos_enc_table", is_test,
    )
    y = dec_in
    for _ in range(hp.n_layer):
        if remat:
            y = layers.recompute(
                lambda h: decoder_layer(
                    h, enc_out, trg_slf_attn_bias, trg_src_attn_bias, hp,
                    is_test, self_kpad=trg_kpad_bias, cross_kpad=cross_kpad),
                y)
        else:
            y = decoder_layer(
                y, enc_out, trg_slf_attn_bias, trg_src_attn_bias, hp, is_test,
                self_kpad=trg_kpad_bias, cross_kpad=cross_kpad,
            )

    logits = layers.fc(y, size=hp.trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False, param_attr=_pa("softmax_out.w"))
    return logits


def wmt_transformer_program(hp=ModelHyperParams, src_len=64, trg_len=64, learning_rate=2.0, warmup_steps=4000, is_test=False, use_bf16=False, mesh=None):
    """Build (main, startup, feed names, [loss]) for training — the analog of
    the reference's transformer train program w/ label smoothing + noam lr.

    use_bf16 applies the AMP rewrite (bf16 matmuls on the MXU, f32 master
    weights) before minimize so grads differentiate through the casts.
    hp.fused_attn additionally routes attention through the fused op; the
    decoder key-padding row is derived in-graph from the lbl_weight feed
    (weight 1 = real token), so the feed contract is unchanged."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        src = layers.data("src_word", shape=[src_len], dtype="int64")
        trg = layers.data("trg_word", shape=[trg_len], dtype="int64")
        lbl = layers.data("lbl_word", shape=[trg_len], dtype="int64")
        src_bias = layers.data("src_slf_attn_bias", shape=[1, 1, src_len], dtype="float32")
        trg_bias = layers.data("trg_slf_attn_bias", shape=[1, trg_len, trg_len], dtype="float32")
        cross_bias = layers.data("trg_src_attn_bias", shape=[1, 1, src_len], dtype="float32")
        weights = layers.data("lbl_weight", shape=[trg_len], dtype="float32")

        trg_kpad = None
        if getattr(hp, "fused_attn", False):
            # weight w ∈ {0,1} -> bias 0 at real tokens, -1e9 at padding
            trg_kpad = layers.scale(weights, scale=1e9, bias=-1e9)
            trg_kpad.stop_gradient = True
        logits = transformer(src, trg, src_bias, trg_bias, cross_bias, hp,
                             is_test, trg_kpad_bias=trg_kpad)
        label_oh = layers.one_hot(lbl, hp.trg_vocab_size)
        if hp.label_smooth_eps:
            label_oh = layers.label_smooth(label_oh, epsilon=hp.label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(logits, label_oh, soft_label=True)
        weighted = layers.elementwise_mul(cost, layers.unsqueeze(weights, [2]))
        sum_cost = layers.reduce_sum(weighted)
        token_count = layers.reduce_sum(weights)
        avg_cost = layers.elementwise_div(sum_cost, token_count)

        # fold the one_hot -> label_smooth -> soft-label-xent chain into
        # the closed-form smooth_label_xent op: at bench config the chain
        # materializes three [B*T, V] f32 arrays (~4 GB/step) for a
        # quantity computable from logits + int labels alone
        from ..transpiler.pass_registry import apply_pass

        apply_pass(main, "smooth_label_xent_fuse_pass")
        # then fold the [H, V] projection INTO the loss (logits-free
        # fused cross-entropy: the [B, T, V] f32 logits tensor never
        # reaches HBM under FLAGS_use_pallas) and collapse the FFN
        # mul+bias+act / residual-add+layer_norm chains onto the
        # matmul-epilogue kernel layer
        apply_pass(main, "linear_xent_fuse_pass")
        apply_pass(main, "matmul_epilogue_fuse_pass")

        if use_bf16:
            # AMP rides the pass registry (bf16 MXU compute, f32 master
            # params — the optimizer state and param vars stay f32)
            apply_pass(main, "bf16_amp_pass")
        # HBM-budgeted rematerialization (FLAGS_hbm_budget_bytes): after
        # the fuse/AMP rewrites (segments carry the final op mix), before
        # minimize (grads differentiate through the recompute ops)
        from ..transpiler.remat import maybe_remat

        maybe_remat(main, avg_cost, is_test, mesh=mesh)
        if not is_test:
            lr = layers.learning_rate_scheduler.noam_decay(hp.d_model, warmup_steps)
            lr = layers.scale(lr, scale=float(learning_rate))
            opt = fluid.optimizer.Adam(learning_rate=lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
            opt.minimize(avg_cost)
    if mesh is not None:
        # GSPMD training stamp: transformer-family rules lifted to
        # training names (grads + Adam moments shard like their param),
        # batch feeds over the mesh's dp axis — no model edits
        from ..parallel.partition_rules import (annotate_spmd,
                                                train_partition_rules_for)

        annotate_spmd(main, mesh, train_partition_rules_for(
            getattr(hp, "partition_family", "transformer")))
    feeds = [
        "src_word", "trg_word", "lbl_word", "src_slf_attn_bias",
        "trg_slf_attn_bias", "trg_src_attn_bias", "lbl_weight",
    ]
    return main, startup, feeds, [avg_cost, token_count]


NEG_BIAS = -1e9  # the shared "masked" sentinel across train/infer masks


def pad_bias(lens, max_len):
    """[B] lengths -> [B, 1, 1, max_len] additive key-padding bias."""
    lens = np.asarray(lens).reshape(-1)
    pad = np.arange(max_len)[None, :] >= lens[:, None]
    return np.where(pad, NEG_BIAS, 0.0).astype("float32")[:, None, None, :]


def causal_plus_pad_bias(lens, max_len):
    """[B] lengths -> [B, 1, T, T] causal + key-padding decoder bias."""
    lens = np.asarray(lens).reshape(-1)
    causal = np.triu(np.ones((max_len, max_len)), k=1) * NEG_BIAS
    pad = np.arange(max_len)[None, :] >= lens[:, None]
    bias = np.where(pad[:, None, :], NEG_BIAS, 0.0) + causal[None, :, :]
    return bias[:, None, :, :].astype("float32")


def make_fake_batch(batch_size, src_len, trg_len, hp=ModelHyperParams, seed=0):
    """Synthetic padded batch + masks (host-side; analog of the data reader)."""
    rng = np.random.RandomState(seed)
    src = rng.randint(1, hp.src_vocab_size, (batch_size, src_len)).astype("int64")
    trg = rng.randint(1, hp.trg_vocab_size, (batch_size, trg_len)).astype("int64")
    lbl = rng.randint(1, hp.trg_vocab_size, (batch_size, trg_len)).astype("int64")
    src_lens = rng.randint(src_len // 2, src_len + 1, (batch_size,))
    trg_lens = rng.randint(trg_len // 2, trg_len + 1, (batch_size,))

    src_bias = pad_bias(src_lens, src_len)
    trg_bias = causal_plus_pad_bias(trg_lens, trg_len)
    cross_bias = pad_bias(src_lens, src_len)
    weights = (np.arange(trg_len)[None, :] < trg_lens[:, None]).astype("float32")
    return {
        "src_word": src,
        "trg_word": trg,
        "lbl_word": lbl,
        "src_slf_attn_bias": src_bias,
        "trg_slf_attn_bias": trg_bias,
        "trg_src_attn_bias": cross_bias,
        "lbl_weight": weights,
    }


def transformer_logits_program(hp=ModelHyperParams, src_len=64, trg_len=64):
    """Inference program fetching [B, Tt, trg_vocab] logits — the
    greedy/beam decode-step workhorse (static shapes, one compile).
    Built under unique_name.guard() so it shares weights by name with a
    wmt_transformer_program trained earlier in the same scope."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup), unique_name.guard():
        src = layers.data("src_word", shape=[src_len], dtype="int64")
        trg = layers.data("trg_word", shape=[trg_len], dtype="int64")
        src_bias = layers.data("src_slf_attn_bias", shape=[1, 1, src_len], dtype="float32")
        trg_bias = layers.data("trg_slf_attn_bias", shape=[1, trg_len, trg_len], dtype="float32")
        cross_bias = layers.data("trg_src_attn_bias", shape=[1, 1, src_len], dtype="float32")
        trg_kpad = None
        if getattr(hp, "fused_attn", False):
            # the dense decoder bias's LAST causal row is pure key-padding
            # (causal contributes 0 there): extract it as the rank-1 bias
            # the fused path needs
            last_row = layers.slice(
                trg_bias, axes=[2], starts=[trg_len - 1], ends=[trg_len]
            )
            trg_kpad = layers.reshape(last_row, [-1, trg_len])
            trg_kpad.stop_gradient = True
        logits = transformer(src, trg, src_bias, trg_bias, cross_bias, hp,
                             is_test=True, trg_kpad_bias=trg_kpad)
    feeds = ["src_word", "trg_word", "src_slf_attn_bias",
             "trg_slf_attn_bias", "trg_src_attn_bias"]
    return main, startup, feeds, [logits]


def _translate_prologue(main, src_ids, src_lens, max_out_len):
    """Shared decode prologue: program widths, src validation, padding bias."""
    blk = main.global_block()
    src_len = int(blk.vars["src_word"].shape[1])
    trg_len = int(blk.vars["trg_word"].shape[1])
    max_out_len = min(max_out_len or trg_len, trg_len)
    src_ids = np.asarray(src_ids, "int64")
    b, p = src_ids.shape
    assert p == src_len, "src must be padded to the program's %d" % src_len
    src_lens = np.asarray(src_lens).reshape(-1)
    return src_ids, src_lens, pad_bias(src_lens, src_len), trg_len, max_out_len, b


def greedy_translate(exe, main, fetches, src_ids, src_lens, bos_id, eos_id,
                     max_out_len=None, pad_id=0):
    """Greedy decoding on a fixed-shape logits program (the reference
    transformer's inference role, TPU-style: static shapes, one compile;
    causal masking hides the padded target tail each step).

    src_ids [B, Ts] int64, src_lens [B] — returns [B, T_out] int64 rows
    starting with bos_id; generation stops early once every row emitted
    eos_id."""
    src_ids, src_lens, src_bias, trg_len, max_out_len, b = _translate_prologue(
        main, src_ids, src_lens, max_out_len
    )
    trg = np.full((b, trg_len), pad_id, "int64")
    trg[:, 0] = bos_id
    done = np.zeros(b, bool)
    cur = 1
    while cur < max_out_len and not done.all():
        trg_bias = causal_plus_pad_bias(np.full(b, cur), trg_len)
        feed = {
            "src_word": src_ids,
            "trg_word": trg,
            "src_slf_attn_bias": src_bias,
            "trg_slf_attn_bias": trg_bias,
            "trg_src_attn_bias": src_bias,
        }
        (logits,) = exe.run(main, feed=feed, fetch_list=fetches)
        nxt = np.asarray(logits)[:, cur - 1, :].argmax(axis=-1)
        nxt = np.where(done, pad_id, nxt)
        trg[:, cur] = nxt
        done |= nxt == eos_id
        cur += 1
    return trg[:, :cur]


def beam_translate(exe, main, fetches, src_ids, src_lens, bos_id, eos_id,
                   beam_size=4, max_out_len=None, pad_id=0,
                   length_penalty=0.0):
    """Beam-search decoding on the transformer_logits_program (same feed
    contract as greedy_translate).  Returns (ids [B, T_out], scores [B])."""
    from ..contrib.decoder.beam_search_decoder import full_sequence_beam_search

    src_ids, src_lens, src_bias, trg_len, max_out_len, b = _translate_prologue(
        main, src_ids, src_lens, max_out_len
    )
    src_rep = np.repeat(src_ids, beam_size, axis=0)
    src_bias_rep = np.repeat(src_bias, beam_size, axis=0)

    trg0 = np.full((b, trg_len), pad_id, "int64")
    trg0[:, 0] = bos_id

    def logits_fn(rows, cur):
        feed = {
            "src_word": src_rep,
            "trg_word": rows,
            "src_slf_attn_bias": src_bias_rep,
            "trg_slf_attn_bias": causal_plus_pad_bias(
                np.full(rows.shape[0], cur), trg_len
            ),
            "trg_src_attn_bias": src_bias_rep,
        }
        (logits,) = exe.run(main, feed=feed, fetch_list=fetches)
        return np.asarray(logits)[:, cur - 1, :]

    return full_sequence_beam_search(
        logits_fn, trg0, 1, beam_size, max_out_len, eos_id, pad_id,
        length_penalty,
    )


def transformer_decode_programs(hp=ModelHyperParams, batch=1, src_len=64,
                                t_max=None, width=1):
    """KV-cached seq2seq decoding, split into two programs sharing
    persistable state (and weight names with wmt_transformer_program /
    transformer_logits_program built in the same process):

      enc_main:  feeds src_word [B, Ts] + src_slf_attn_bias [B,1,1,Ts];
                 runs the encoder ONCE, persisting enc_out and the
                 cross-attention key-padding row as scope state.
      step_main: feeds trg_tok [B, W] + pos [1] (+ pos_vec [W] when
                 width W > 1); one cached decoder step (self-attention
                 over per-layer K/V caches — offset-causal for W > 1 —
                 and W-query cross-attention over the persisted
                 enc_out); fetches logits [B, trg_vocab] (W == 1) or
                 [B, W, trg_vocab].
      cache_startup: zeroes all the persistable decode state.

    Per generated token this is O((t_max + src_len) d) work instead of
    the full re-decode's O(t_max^2 d); width > 1 scores W known target
    positions per dispatch — the candidate-RESCORING workhorse (force-
    decode a hypothesis in ceil(T/W) MXU-shaped dispatches).  Returns
    (enc_main, step_main, cache_startup, enc_feeds, step_feeds,
    enc_fetch, step_fetch)."""
    import paddle_tpu as fluid

    t_max = t_max or hp.max_length
    assert t_max <= hp.max_length, (
        "t_max %d exceeds hp.max_length %d" % (t_max, hp.max_length))
    width = int(width)
    assert 1 <= width <= t_max, (width, t_max)
    dh = hp.d_model // hp.n_head
    enc_main = fluid.Program()
    step_main = fluid.Program()
    cache_startup = fluid.Program()
    throwaway = fluid.Program()

    with unique_name.guard():
        # ---- encoder program (parameter names: src emb + enc layers) ----
        with fluid.program_guard(enc_main, throwaway):
            src = layers.data("src_word", shape=[batch, src_len],
                              dtype="int64", append_batch_size=False)
            src_bias = layers.data(
                "src_slf_attn_bias", shape=[batch, 1, 1, src_len],
                dtype="float32", append_batch_size=False)
            src_kpad = layers.reshape(src_bias, [-1, src_len])
            x = prepare_embedding(
                src, hp.src_vocab_size, hp.d_model, hp.max_length, 0.0,
                "src_pos_enc_table", is_test=True)
            for _ in range(hp.n_layer):
                x = encoder_layer(x, src_bias, hp, is_test=True,
                                  kpad_bias=src_kpad)
            eb = enc_main.global_block()
            enc_cache = eb.create_var(
                name="tfm_enc_out_cache", shape=[batch, src_len, hp.d_model],
                dtype="float32", persistable=True)
            kpad_cache = eb.create_var(
                name="tfm_cross_kpad_cache", shape=[batch, src_len],
                dtype="float32", persistable=True)
            eb.append_op("assign", inputs={"X": [x]},
                         outputs={"Out": [enc_cache]})
            eb.append_op("assign", inputs={"X": [src_kpad]},
                         outputs={"Out": [kpad_cache]})

        # ---- decode-step program (names continue: trg emb + dec layers) --
        with fluid.program_guard(step_main, throwaway):
            tok = layers.data("trg_tok", shape=[batch, width], dtype="int64",
                              append_batch_size=False)
            pos = layers.data("pos", shape=[1], dtype="int64",
                              append_batch_size=False)
            pos_vec = None
            if width > 1:
                pos_vec = layers.data("pos_vec", shape=[width],
                                      dtype="int64",
                                      append_batch_size=False)
            word = layers.embedding(
                tok, size=[hp.trg_vocab_size, hp.d_model],
                param_attr=ParamAttr(initializer=Normal(0.0, hp.d_model ** -0.5)),
            )  # [B, W, D] (W == 1 squeezes in the lookup)
            word = layers.scale(
                layers.reshape(word, shape=[batch, width, hp.d_model]),
                scale=hp.d_model ** 0.5)
            pos_table = layers.create_parameter(
                shape=[hp.max_length, hp.d_model], dtype="float32",
                name="trg_pos_enc_table",
                attr=ParamAttr(
                    name="trg_pos_enc_table", trainable=False,
                    initializer=_NumpyInit(
                        _pos_encoding_table(hp.max_length, hp.d_model))),
            )
            if width == 1:
                pos_row = layers.reshape(layers.gather(pos_table, pos),
                                         shape=[1, 1, hp.d_model])
                y = layers.elementwise_add(word, pos_row)
            else:
                pos_rows = layers.gather(pos_table, pos_vec)  # [W, D]
                y = layers.elementwise_add(word, pos_rows, axis=1)
            sb = step_main.global_block()
            enc_ref = sb.create_var(
                name="tfm_enc_out_cache", shape=[batch, src_len, hp.d_model],
                dtype="float32", persistable=True)
            kpad_ref = sb.create_var(
                name="tfm_cross_kpad_cache", shape=[batch, src_len],
                dtype="float32", persistable=True)
            from .decode_cache import create_kv_caches

            cache_names = ["tfm_enc_out_cache", "tfm_cross_kpad_cache"]
            kv_caches, kv_names = create_kv_caches(
                sb, "tfm", hp.n_layer, batch, hp.n_head, t_max, dh)
            cache_names += kv_names
            for cache in kv_caches:
                cache["pos"] = pos
                if pos_vec is not None:
                    cache["pos_vec"] = pos_vec
                y = decoder_layer(y, enc_ref, None, None, hp, is_test=True,
                                  cross_kpad=kpad_ref, cache=cache)
            logits = layers.fc(y, size=hp.trg_vocab_size, num_flatten_dims=2,
                               bias_attr=False, param_attr=_pa("softmax_out.w"))
            if width == 1:
                logits = layers.reshape(logits,
                                        shape=[batch, hp.trg_vocab_size])

        # ---- cache zeroing program --------------------------------------
        from .decode_cache import add_cache_zero_fills

        add_cache_zero_fills(cache_startup, [
            (cname, (enc_main.global_block()._find_var_recursive(cname)
                     or step_main.global_block()._find_var_recursive(cname)
                     ).shape)
            for cname in cache_names])

    step_feeds = ["trg_tok", "pos"] + (["pos_vec"] if width > 1 else [])
    return (enc_main, step_main, cache_startup,
            ["src_word", "src_slf_attn_bias"], step_feeds,
            ["tfm_enc_out_cache"], [logits])


def force_decode_logits_cached(exe, programs, src_ids, src_lens, trg_ids):
    """Teacher-forced scoring through the cached decoder: run the
    encoder once, then feed the GIVEN target tokens in ceil(T/W)
    width-W dispatches (programs from transformer_decode_programs
    (width=W)); returns [B, T, V] logits where row t is the
    next-token distribution after trg_ids[:, t] — the candidate-
    RESCORING workhorse (log-prob of a hypothesis without a token
    loop).  The last chunk re-anchors inside the cache bound
    (rewriting identical slots is idempotent)."""
    from .decode_cache import probe_cache_len

    (enc_main, step_main, cache_startup, _enc_feeds, step_feeds,
     _enc_fetch, step_fetch) = programs
    src_ids = np.asarray(src_ids, "int64")
    trg_ids = np.asarray(trg_ids, "int64")
    b, T = trg_ids.shape
    sb = step_main.global_block()
    step_b, width = (int(sb.vars["trg_tok"].shape[0]),
                     int(sb.vars["trg_tok"].shape[1]))
    assert b == step_b, (b, step_b)
    t_max = probe_cache_len(step_main, "tfm")
    assert T <= t_max, (T, t_max)
    src_lens = np.asarray(src_lens).reshape(-1)

    exe.run(cache_startup)
    exe.run(enc_main, feed={
        "src_word": src_ids,
        "src_slf_attn_bias": pad_bias(src_lens, src_ids.shape[1]),
    }, fetch_list=[])

    from .decode_cache import run_chunked_ids

    out = None
    for c0, lg in run_chunked_ids(exe, step_main, step_fetch, trg_ids,
                                  width, t_max, "trg_tok",
                                  has_pos_vec="pos_vec" in step_feeds):
        lg = lg.reshape(b, width, -1)
        if out is None:
            out = np.zeros((b, T, lg.shape[-1]), lg.dtype)
        hi = min(c0 + width, T)
        out[:, c0:hi] = lg[:, :hi - c0]
    return out


def _translate_cached_loop(exe, programs, src_ids, src_lens, bos_id,
                           eos_id, max_out_len, pad_id, pick_fn):
    """Shared driver for cached seq2seq decoding: validate, zero caches,
    run the encoder once, then step the cached decoder; pick_fn(logits
    [B, V]) -> [B] chooses each next token (argmax or sampler)."""
    from .decode_cache import probe_cache_len

    (enc_main, step_main, cache_startup, enc_feeds, step_feeds,
     enc_fetch, step_fetch) = programs
    src_ids = np.asarray(src_ids, "int64")
    b, _ = src_ids.shape
    sb = step_main.global_block()
    step_b = int(sb.vars["trg_tok"].shape[0])
    assert b == step_b, (
        "src batch %d != decode programs' static batch %d" % (b, step_b))
    t_max = probe_cache_len(step_main, "tfm")
    max_out_len = min(max_out_len or t_max, t_max)
    src_lens = np.asarray(src_lens).reshape(-1)

    exe.run(cache_startup)
    # no fetch: the encoder's persistable writes survive DCE, and fetching
    # the [B, Ts, D] activation would be a pure wasted D2H transfer
    exe.run(enc_main, feed={
        "src_word": src_ids,
        "src_slf_attn_bias": pad_bias(src_lens, src_ids.shape[1]),
    }, fetch_list=[])

    trg = np.full((b, max_out_len), pad_id, "int64")
    trg[:, 0] = bos_id
    done = np.zeros(b, bool)
    cur = 1
    while cur < max_out_len and not done.all():
        (logits,) = exe.run(step_main, feed={
            "trg_tok": trg[:, cur - 1:cur],
            "pos": np.array([cur - 1], "int64"),
        }, fetch_list=step_fetch)
        nxt = np.where(done, pad_id, pick_fn(logits))
        trg[:, cur] = nxt
        done |= nxt == eos_id
        cur += 1
    return trg[:, :cur]


def greedy_translate_cached(exe, programs, src_ids, src_lens, bos_id, eos_id,
                            max_out_len=None, pad_id=0):
    """Greedy decoding through the KV-cached decode programs (the output
    contract of greedy_translate, at O((t_max + Ts) d) per token).
    `programs` is transformer_decode_programs' return tuple."""
    return _translate_cached_loop(
        exe, programs, src_ids, src_lens, bos_id, eos_id, max_out_len,
        pad_id, lambda lg: np.asarray(lg).argmax(axis=-1).astype("int64"))


def beam_translate_cached(exe, programs, src_ids, src_lens, bos_id, eos_id,
                          beam_size=4, max_out_len=None, pad_id=0,
                          length_penalty=0.0):
    """Beam-search decoding through the KV-cached decode programs (built
    with batch = B * beam_size).  Self-attention caches shuffle to the
    surviving beams each step; the encoder state is beam-replicated at
    encode time and invariant under the shuffle.  Output contract of
    beam_translate.  Returns (ids [B, T_out], scores [B])."""
    from ..contrib.decoder.beam_search_decoder import incremental_beam_search
    from .decode_cache import make_cache_reorder_program, probe_cache_len

    (enc_main, step_main, cache_startup, enc_feeds, step_feeds,
     enc_fetch, step_fetch) = programs
    src_ids = np.asarray(src_ids, "int64")
    b, _ = src_ids.shape
    sb = step_main.global_block()
    r = int(sb.vars["trg_tok"].shape[0])
    assert r == b * beam_size, (
        "decode programs' batch %d != src batch %d * beam %d"
        % (r, b, beam_size))
    t_max = probe_cache_len(step_main, "tfm")
    max_out_len = min(max_out_len or t_max, t_max)
    src_lens = np.asarray(src_lens).reshape(-1)

    exe.run(cache_startup)
    exe.run(enc_main, feed={
        "src_word": np.repeat(src_ids, beam_size, axis=0),
        "src_slf_attn_bias": np.repeat(
            pad_bias(src_lens, src_ids.shape[1]), beam_size, axis=0),
    }, fetch_list=[])

    # only the per-layer self-attention caches follow the beams
    reorder = make_cache_reorder_program(
        [(n, v.shape) for n, v in sb.vars.items()
         if n.startswith(("tfm_kcache_", "tfm_vcache_"))], r)

    bos = np.full((r, 1), bos_id, "int64")
    (first,) = exe.run(step_main, feed={
        "trg_tok": bos, "pos": np.array([0], "int64")}, fetch_list=step_fetch)

    def step_fn(tokens, pos):
        (lg,) = exe.run(step_main, feed={
            "trg_tok": tokens, "pos": np.array([pos], "int64")},
            fetch_list=step_fetch)
        return lg

    def reorder_fn(rows):
        exe.run(reorder, feed={"parents": rows.astype("int64")},
                fetch_list=[])

    prompt = np.full((b, 1), bos_id, "int64")
    return incremental_beam_search(
        step_fn, reorder_fn, first, prompt, 1, beam_size, max_out_len,
        eos_id, pad_id, length_penalty)


def sample_translate_cached(exe, programs, src_ids, src_lens, bos_id,
                            eos_id, max_out_len=None, temperature=1.0,
                            top_k=0, top_p=1.0, seed=None, pad_id=0):
    """Stochastic seq2seq decoding through the KV-cached programs:
    temperature / top-k / nucleus filtering with seeded numpy sampling
    (the sampling twin of greedy_translate_cached)."""
    from .decode_cache import sample_from_logits

    rng = np.random.RandomState(seed)
    return _translate_cached_loop(
        exe, programs, src_ids, src_lens, bos_id, eos_id, max_out_len,
        pad_id,
        lambda lg: sample_from_logits(lg, rng, temperature, top_k, top_p))
