"""Seq2seq with attention (book/test_machine_translation +
benchmark/fluid/models/machine_translation roles).

Encoder: embedding -> GRU over padded source.  Decoder: GRU cell with
Bahdanau-style additive attention over encoder states, teacher-forced at
training.  Inference reuses the same cell via the contrib
BeamSearchDecoder (host loop over one compiled step) — the TPU
re-expression of the reference's While/DynamicRNN decode.
"""


from .. import layers


def encoder(src_ids, src_vocab, embed_dim=32, hidden_dim=32, seq_len=None):
    emb = layers.embedding(src_ids, size=[src_vocab, embed_dim], dtype="float32")
    proj = layers.fc(emb, size=hidden_dim * 3, num_flatten_dims=2)
    return layers.dynamic_gru(proj, size=hidden_dim, seq_len=seq_len)


def _attention(dec_state, enc_out, hidden_dim):
    """Additive attention: scores = v . tanh(W_enc h_enc + W_dec h_dec)."""
    dec_proj = layers.fc(dec_state, size=hidden_dim, bias_attr=False)
    enc_proj = layers.fc(enc_out, size=hidden_dim, num_flatten_dims=2,
                         bias_attr=False)
    # [batch, T, H] + [batch, 1, H]
    mix = layers.tanh(
        layers.elementwise_add(enc_proj, layers.unsqueeze(dec_proj, [1]))
    )
    scores = layers.fc(mix, size=1, num_flatten_dims=2, bias_attr=False)
    scores = layers.squeeze(scores, [2])  # [batch, T]
    weights = layers.softmax(scores)  # [batch, T]
    ctx = layers.matmul(layers.unsqueeze(weights, [1]), enc_out)  # [b,1,H]
    return layers.squeeze(ctx, [1])


def decoder_train(enc_out, tgt_ids, tgt_vocab, embed_dim=32, hidden_dim=32):
    """Teacher-forced decoder over padded targets; returns [b, T, vocab]
    softmax.  The per-step GRU cell + attention run under the padded-time
    GRU op; here we use a simple unrolled-free formulation: project the
    attention context per step with a time-distributed cell approximated by
    dynamic_gru over [emb ; repeated mean-context]."""
    emb = layers.embedding(tgt_ids, size=[tgt_vocab, embed_dim], dtype="float32")
    # global (mean-pooled) encoder summary as the stand-in context per step;
    # per-step attention happens in the inference cell (decoder_step)
    ctx = layers.reduce_mean(enc_out, dim=1, keep_dim=True)
    ctx_rep = layers.expand(ctx, [1, emb.shape[1], 1])
    cell_in = layers.concat([emb, ctx_rep], axis=2)
    proj = layers.fc(cell_in, size=hidden_dim * 3, num_flatten_dims=2)
    dec = layers.dynamic_gru(proj, size=hidden_dim)
    return layers.fc(dec, size=tgt_vocab, num_flatten_dims=2, act="softmax")


def build_seq2seq_train(src_vocab, tgt_vocab, max_src=16, max_tgt=16,
                        embed_dim=32, hidden_dim=32):
    """Returns (feeds, avg_cost)."""
    src = layers.data("src_word_id", shape=[max_src], dtype="int64")
    tgt = layers.data("target_language_word", shape=[max_tgt], dtype="int64")
    lbl = layers.data("target_language_next_word", shape=[max_tgt], dtype="int64")

    enc_out = encoder(src, src_vocab, embed_dim, hidden_dim)
    probs = decoder_train(enc_out, tgt, tgt_vocab, embed_dim, hidden_dim)
    flat = layers.reshape(probs, [-1, tgt_vocab])
    cost = layers.cross_entropy(flat, layers.reshape(lbl, [-1, 1]))
    return [src, tgt, lbl], layers.mean(cost)


def build_decode_step(src_vocab, tgt_vocab, max_src=16, embed_dim=32,
                      hidden_dim=32):
    """One decode step program for the BeamSearchDecoder: feeds
    (src ids, current token, prev hidden) -> (log-probs, new hidden),
    sharing parameter names with the training program."""
    src = layers.data("src_word_id", shape=[max_src], dtype="int64")
    cur = layers.data("cur_token", shape=[1], dtype="int64")
    prev_h = layers.data("prev_hidden", shape=[hidden_dim])

    enc_out = encoder(src, src_vocab, embed_dim, hidden_dim)
    att = _attention(prev_h, enc_out, hidden_dim)
    emb = layers.embedding(cur, size=[tgt_vocab, embed_dim], dtype="float32")
    emb = layers.reshape(emb, [-1, embed_dim])
    cell_in = layers.concat([emb, att], axis=1)
    # single GRU step: reuse the padded-gru over T=1
    proj = layers.fc(layers.unsqueeze(cell_in, [1]), size=hidden_dim * 3,
                     num_flatten_dims=2)
    dec = layers.dynamic_gru(proj, size=hidden_dim, h_0=prev_h)
    new_h = layers.squeeze(dec, [1])
    probs = layers.fc(new_h, size=tgt_vocab, act="softmax")
    logp = layers.log(probs)
    return [src, cur, prev_h], logp, new_h
