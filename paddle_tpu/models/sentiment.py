"""Text-classification models (book/test_understand_sentiment +
benchmark/fluid/models/stacked_dynamic_lstm roles): conv and stacked-LSTM
nets over padded token sequences with length masks."""

from .. import layers, nets


def convolution_net(data, seq_len, input_dim, class_dim=2, emb_dim=32,
                    hid_dim=32):
    """Two parallel sequence-conv+pool branches -> softmax (book conv net)."""
    emb = layers.embedding(data, size=[input_dim, emb_dim], dtype="float32")
    conv_3 = nets.sequence_conv_pool(
        emb, num_filters=hid_dim, filter_size=3, act="tanh", pool_type="sqrt",
        seq_len=seq_len,
    )
    conv_4 = nets.sequence_conv_pool(
        emb, num_filters=hid_dim, filter_size=4, act="tanh", pool_type="sqrt",
        seq_len=seq_len,
    )
    return layers.fc([conv_3, conv_4], size=class_dim, act="softmax")


def stacked_lstm_net(data, seq_len, input_dim, class_dim=2, emb_dim=32,
                     hid_dim=32, stacked_num=3):
    """Stacked bi-directional-ish LSTM (alternate reversed layers) with
    max pooling over time (book stacked_lstm_net / stacked_dynamic_lstm)."""
    assert stacked_num % 2 == 1
    emb = layers.embedding(data, size=[input_dim, emb_dim], dtype="float32")

    # fluid dynamic_lstm contract: input pre-projected to 4*hidden
    fc1 = layers.fc(emb, size=hid_dim * 4, num_flatten_dims=2)
    lstm1, _ = layers.dynamic_lstm(fc1, size=hid_dim * 4, seq_len=seq_len)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        # multi-input fc == concat+fc (separate weights, summed)
        fc = layers.fc(inputs, size=hid_dim * 4, num_flatten_dims=2)
        lstm, _ = layers.dynamic_lstm(
            fc, size=hid_dim * 4, is_reverse=(i % 2) == 0, seq_len=seq_len
        )
        inputs = [fc, lstm]

    # max over time (padded positions masked to -inf by seq_len-aware pool)
    fc_last = layers.sequence_pool(inputs[0], pool_type="max", seq_len=seq_len)
    lstm_last = layers.sequence_pool(inputs[1], pool_type="max", seq_len=seq_len)
    return layers.fc([fc_last, lstm_last], size=class_dim, act="softmax")
