"""Model zoo (benchmark/fluid/models + tests/book model roles)."""

from . import (
    ctr_deepfm,
    machine_translation,
    mnist,
    resnet,
    se_resnext,
    sentiment,
    stacked_dynamic_lstm,
    transformer,
    vgg,
    word2vec,
)
