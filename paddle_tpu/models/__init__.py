"""paddle_tpu.models"""
