"""Model zoo (benchmark/fluid/models + tests/book model roles)."""

from . import (
    bert,
    ctr_deepfm,
    gpt2,
    machine_translation,
    mnist,
    resnet,
    se_resnext,
    sentiment,
    stacked_dynamic_lstm,
    transformer,
    vgg,
    word2vec,
)
