"""MNIST convnet (capability mirror of benchmark/fluid/models/mnist.py)."""

from .. import layers, nets

__all__ = ["cnn_model", "mlp_model"]


def cnn_model(data, class_dim=10):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2, pool_stride=2, act="relu"
    )
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1,
        filter_size=5,
        num_filters=50,
        pool_size=2,
        pool_stride=2,
        act="relu",
    )
    return layers.fc(input=conv_pool_2, size=class_dim, act="softmax")


def mlp_model(data, class_dim=10, hidden=(128, 64)):
    x = data
    for h in hidden:
        x = layers.fc(x, size=h, act="relu")
    return layers.fc(x, size=class_dim, act="softmax")
