"""ResNet-50/101/152 model builder.

Capability mirror of the reference's benchmark model
(`benchmark/fluid/models/resnet.py:47,171` — conv_bn_layer + bottleneck
stacks), re-built on paddle_tpu layers.  The whole train step (fwd + bwd +
SGD/momentum) compiles to one XLA program; conv+BN+relu fuse on TPU without
the reference's fuse passes.
"""

from .. import layers

__all__ = ["resnet_imagenet", "resnet_cifar10", "resnet50", "ResNetConfig"]


class ResNetConfig:
    depth_blocks = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu", is_test=False):
    conv = layers.conv2d(
        input=input,
        num_filters=ch_out,
        filter_size=filter_size,
        stride=stride,
        padding=padding,
        act=None,
        bias_attr=False,
    )
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None, is_test)
    return input


def basicblock(input, ch_out, stride, is_test=False):
    short = shortcut(input, ch_out, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, None, is_test)
    return layers.elementwise_add(short, conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False):
    short = shortcut(input, ch_out * 4, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, None, is_test)
    return layers.elementwise_add(short, conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_test=False):
    res_out = block_func(input, ch_out, stride, is_test)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test)
    return res_out


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    cfg = ResNetConfig.depth_blocks[depth]
    conv1 = conv_bn_layer(input, 64, 7, 2, 3, is_test=is_test)
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2, pool_padding=1, pool_type="max")
    res1 = layer_warp(bottleneck, pool1, 64, cfg[0], 1, is_test)
    res2 = layer_warp(bottleneck, res1, 128, cfg[1], 2, is_test)
    res3 = layer_warp(bottleneck, res2, 256, cfg[2], 2, is_test)
    res4 = layer_warp(bottleneck, res3, 512, cfg[3], 2, is_test)
    pool2 = layers.pool2d(res4, pool_size=7, pool_type="avg", global_pooling=True)
    out = layers.fc(input=pool2, size=class_dim, act="softmax")
    return out


def resnet50(input, class_dim=1000, is_test=False):
    return resnet_imagenet(input, class_dim, 50, is_test)


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, 16, 3, 1, 1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test)
    pool = layers.pool2d(res3, pool_size=8, pool_type="avg", global_pooling=True)
    out = layers.fc(input=pool, size=class_dim, act="softmax")
    return out


def build_resnet_train_program(
    batch_size=None,
    image_shape=(3, 224, 224),
    class_dim=1000,
    depth=50,
    lr=0.1,
    optimizer="momentum",
    dtype="float32",
    use_bf16=False,
    use_nhwc=False,
    use_reader_op=False,
    reader_capacity=8,
):
    """Build (main_program, startup_program, feeds, fetches) for training —
    convenience mirroring the benchmark driver's model setup.  use_bf16
    applies the AMP rewrite (bf16 convs/matmuls on the MXU, f32 master
    weights) before the optimizer pass.  use_nhwc converts the conv trunk
    to channels-last via the nhwc_layout_pass (run first, so the inserted
    transposes ride the AMP trunk propagation).  use_reader_op builds the
    `--use_reader_op` fast path (fluid_benchmark.py): inputs come from an
    in-program py_reader instead of feed, returned as a 5th element."""
    import paddle_tpu as fluid

    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        if use_reader_op:
            reader = layers.py_reader(
                capacity=reader_capacity,
                shapes=[[-1] + list(image_shape), [-1, 1]],
                dtypes=[dtype, "int64"],
            )
            img, label = layers.read_file(reader)
        else:
            reader = None
            img = layers.data("image", shape=list(image_shape), dtype=dtype)
            label = layers.data("label", shape=[1], dtype="int64")
        predict = resnet_imagenet(img, class_dim, depth)
        cost = layers.cross_entropy(input=predict, label=label)
        avg_cost = layers.mean(cost)
        acc = layers.accuracy(input=predict, label=label)
        if use_nhwc:
            from paddle_tpu.transpiler.layout_transpiler import rewrite_nhwc

            rewrite_nhwc(main)
        if use_bf16:
            # AMP rides the pass registry (bf16 MXU compute; master
            # params and optimizer state stay f32) — applied before
            # minimize so grads differentiate through the casts
            from paddle_tpu.transpiler.pass_registry import apply_pass

            apply_pass(main, "bf16_amp_pass")
        # HBM-budgeted remat: resnet stage boundaries detected from the
        # op graph (FLAGS_hbm_budget_bytes; no-op when unset)
        from paddle_tpu.transpiler.remat import maybe_remat

        maybe_remat(main, avg_cost)
        if optimizer == "momentum":
            opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        else:
            opt = fluid.optimizer.SGD(learning_rate=lr)
        opt.minimize(avg_cost)
    if use_reader_op:
        return main, startup, [], [avg_cost, acc], reader
    return main, startup, ["image", "label"], [avg_cost, acc]


def build_resnet_preprocess_train_program(
    batch_size=None,
    image_shape=(224, 224, 3),
    class_dim=1000,
    depth=50,
    lr=0.1,
    raw_margin=32,
    use_bf16=False,
    use_nhwc=False,
):
    """ResNet with IN-GRAPH imagenet preprocessing — the
    `resnet_with_preprocess` cell of the reference benchmark matrix
    (`benchmark/fluid/models/resnet_with_preprocess.py:201-213`): uint8
    HWC input, random_crop -> cast -> HWC->CHW transpose -> /255 ->
    per-channel mean/std normalize, all compiled into the train step (on
    TPU the whole chain fuses into the first conv's input read, so the
    host feeds raw uint8 bytes — 4x less H2D traffic than f32).  The
    feed is `raw_margin` pixels larger than `image_shape` on each
    spatial dim, so the random crop actually augments (the reference
    crops a larger decoded image)."""
    import numpy as np

    import paddle_tpu as fluid

    raw_shape = [image_shape[0] + raw_margin, image_shape[1] + raw_margin,
                 image_shape[2]]
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("image", shape=raw_shape, dtype="uint8")
        label = layers.data("label", shape=[1], dtype="int64")
        crop = layers.random_crop(img, shape=list(image_shape))
        casted = layers.cast(crop, "float32")
        trans = layers.transpose(casted, [0, 3, 1, 2]) / 255.0
        img_mean = layers.assign(
            np.array([0.485, 0.456, 0.406], "float32").reshape(3, 1, 1))
        img_std = layers.assign(
            np.array([0.229, 0.224, 0.225], "float32").reshape(3, 1, 1))
        h = layers.elementwise_sub(trans, img_mean, axis=1)
        h = layers.elementwise_div(h, img_std, axis=1)
        predict = resnet_imagenet(h, class_dim, depth)
        cost = layers.cross_entropy(input=predict, label=label)
        avg_cost = layers.mean(cost)
        acc = layers.accuracy(input=predict, label=label)
        if use_nhwc:
            from paddle_tpu.transpiler.layout_transpiler import rewrite_nhwc

            rewrite_nhwc(main)
        if use_bf16:
            from paddle_tpu.contrib.mixed_precision import rewrite_bf16

            rewrite_bf16(main)
        opt = fluid.optimizer.Momentum(learning_rate=lr, momentum=0.9)
        opt.minimize(avg_cost)
    return main, startup, ["image", "label"], [avg_cost, acc]
