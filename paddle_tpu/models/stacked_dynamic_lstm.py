"""Stacked dynamic-LSTM text classifier (capability mirror of
benchmark/fluid/models/stacked_dynamic_lstm.py): embedding -> N stacked
scan-backed LSTM layers -> max pool over time -> softmax, on the padded
(+seq_len) sequence representation."""

from .. import layers

__all__ = ["build_stacked_lstm_train"]


def build_stacked_lstm_train(
    dict_size,
    seq_len_max,
    emb_dim=64,
    hidden_dim=64,
    stacked_num=3,
    class_dim=2,
):
    """Returns (feed names, avg_loss, accuracy)."""
    from .sentiment import stacked_lstm_net

    data = layers.data("words", shape=[seq_len_max], dtype="int64")
    seq_len = layers.data("seq_len", shape=[], dtype="int64")
    label = layers.data("label", shape=[1], dtype="int64")
    pred = stacked_lstm_net(
        data,
        seq_len,
        dict_size,
        class_dim=class_dim,
        emb_dim=emb_dim,
        hid_dim=hidden_dim,
        stacked_num=stacked_num,
    )
    loss = layers.mean(layers.cross_entropy(pred, label))
    acc = layers.accuracy(input=pred, label=label)
    return ["words", "seq_len", "label"], loss, acc
