"""DeepFM CTR model (dist_ctr.py / DeepFM benchmark role;
BASELINE config 4 "DeepFM sparse CTR").

Sparse id features -> (first-order weights) + (FM pairwise interactions
via the sum-square trick) + (DNN over concatenated embeddings) -> sigmoid.
Embedding lookups are the sparse path (lookup_table gather; SelectedRows-
style segment-sum grads; is_distributed routes through the pserver
prefetch ops when transpiled)."""

from .. import ParamAttr, layers


def deepfm(sparse_ids, dense_input, sparse_field_dims, embed_dim=8,
           dnn_dims=(32, 32), is_sparse=False, is_distributed=False):
    """sparse_ids: list of int64 [batch, 1] vars (one per field);
    dense_input: [batch, D] float var or None.
    is_distributed routes the embedding tables through the pserver
    prefetch/send_sparse path when transpiled (the planet-scale sparse
    scenario: high row-churn over sharded tables).
    Returns sigmoid CTR prediction [batch, 1]."""
    # first order: per-field scalar weight
    first = []
    for i, (ids, dim) in enumerate(zip(sparse_ids, sparse_field_dims)):
        w = layers.embedding(
            ids, size=[dim, 1], dtype="float32", is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr=ParamAttr(name="fm_w1_%d" % i),
        )
        first.append(layers.reshape(w, [-1, 1]))
    y_first = layers.sum(first)

    # second order: FM sum-square trick over field embeddings
    embs = []
    for i, (ids, dim) in enumerate(zip(sparse_ids, sparse_field_dims)):
        e = layers.embedding(
            ids, size=[dim, embed_dim], dtype="float32", is_sparse=is_sparse,
            is_distributed=is_distributed,
            param_attr=ParamAttr(name="fm_v_%d" % i),
        )
        embs.append(layers.reshape(e, [-1, 1, embed_dim]))
    stacked = layers.concat(embs, axis=1)  # [b, fields, k]
    sum_sq = layers.pow(layers.reduce_sum(stacked, dim=1), 2.0)
    sq_sum = layers.reduce_sum(layers.pow(stacked, 2.0), dim=1)
    y_second = layers.scale(
        layers.reduce_sum(layers.elementwise_sub(sum_sq, sq_sum), dim=1,
                          keep_dim=True),
        scale=0.5,
    )

    # deep part
    deep_in = layers.reshape(stacked, [-1, len(sparse_ids) * embed_dim])
    if dense_input is not None:
        deep_in = layers.concat([deep_in, dense_input], axis=1)
    for d in dnn_dims:
        deep_in = layers.fc(deep_in, size=d, act="relu")
    y_deep = layers.fc(deep_in, size=1)

    logit = layers.elementwise_add(
        layers.elementwise_add(y_first, y_second), y_deep
    )
    return layers.sigmoid(logit)


def build_deepfm_train(sparse_field_dims, dense_dim=4, embed_dim=8,
                       is_sparse=False, with_auc=False,
                       is_distributed=False):
    """Returns (feeds, avg_loss, pred) — or, with_auc=True, (feeds,
    avg_loss, pred, auc, batch_auc): the reference CTR-eval workflow
    (dist_ctr.py) with the in-graph streaming layers.auc — global AUC
    plus the sliding-window batch AUC over the last 20 batches."""
    sparse_ids = [
        layers.data("C%d" % i, shape=[1], dtype="int64")
        for i in range(len(sparse_field_dims))
    ]
    dense = layers.data("dense", shape=[dense_dim]) if dense_dim else None
    label = layers.data("click", shape=[1])
    pred = deepfm(sparse_ids, dense, sparse_field_dims, embed_dim,
                  is_sparse=is_sparse, is_distributed=is_distributed)
    loss = layers.mean(layers.log_loss(pred, label, epsilon=1e-6))
    feeds = sparse_ids + ([dense] if dense is not None else []) + [label]
    if with_auc:
        auc_var, batch_auc, _states = layers.auc(
            layers.reshape(pred, [-1]), layers.cast(label, "int64"),
            num_thresholds=2 ** 12 - 1, slide_steps=20)
        return feeds, loss, pred, auc_var, batch_auc
    return feeds, loss, pred
