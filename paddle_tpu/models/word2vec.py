"""N-gram word embedding model (book/test_word2vec role;
benchmark word2vec / imikolov dataset shape).

Four context words -> shared embedding -> concat -> hidden -> softmax over
the vocabulary.  Embeddings share one table (param_attr name sharing, the
is_sparse path exercises lookup_table's gather/segment-sum grads).
"""

from .. import ParamAttr, layers


def ngram_model(words, dict_size, embed_size=32, hidden_size=256,
                is_sparse=False):
    """words: list of 4 int64 [batch, 1] vars (first/second/third/fourth).
    Returns softmax predictions [batch, dict_size]."""
    embeds = [
        layers.embedding(
            w,
            size=[dict_size, embed_size],
            dtype="float32",
            is_sparse=is_sparse,
            param_attr=ParamAttr(name="shared_w"),
        )
        for w in words
    ]
    concat = layers.concat(embeds, axis=-1)
    concat = layers.reshape(concat, [0, len(words) * embed_size])
    hidden = layers.fc(concat, size=hidden_size, act="sigmoid")
    return layers.fc(hidden, size=dict_size, act="softmax")


def build_word2vec_train(dict_size, embed_size=32, hidden_size=256,
                         is_sparse=False):
    """Returns (words, next_word, avg_loss, prediction)."""
    names = ["firstw", "secondw", "thirdw", "fourthw"]
    words = [layers.data(n, shape=[1], dtype="int64") for n in names]
    next_word = layers.data("nextw", shape=[1], dtype="int64")
    pred = ngram_model(words, dict_size, embed_size, hidden_size, is_sparse)
    cost = layers.cross_entropy(pred, next_word)
    return words, next_word, layers.mean(cost), pred
