"""VGG-16/19 (capability mirror of benchmark/fluid/models/vgg.py)."""

from .. import layers, nets

__all__ = ["vgg16", "vgg19"]


def _vgg(input, nums, class_dim, is_test=False):
    def conv_block(x, num_filter, groups):
        return nets.img_conv_group(
            input=x,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            pool_type="max",
        )

    conv1 = conv_block(input, 64, nums[0])
    conv2 = conv_block(conv1, 128, nums[1])
    conv3 = conv_block(conv2, 256, nums[2])
    conv4 = conv_block(conv3, 512, nums[3])
    conv5 = conv_block(conv4, 512, nums[4])

    fc1 = layers.fc(input=conv5, size=4096, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test, data_layout="NHWC")
    drop = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop, size=4096, act=None)
    return layers.fc(input=fc2, size=class_dim, act="softmax")


def vgg16(input, class_dim=1000, is_test=False):
    return _vgg(input, [2, 2, 3, 3, 3], class_dim, is_test)


def vgg19(input, class_dim=1000, is_test=False):
    return _vgg(input, [2, 2, 4, 4, 4], class_dim, is_test)
